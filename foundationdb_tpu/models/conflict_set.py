"""TpuConflictSet: the host-facing conflict-detection object.

Plays the role of the reference's ConflictSet + ConflictBatch pair
(fdbserver/include/fdbserver/ConflictSet.h:30-75): persistent MVCC write
history plus a batch-at-a-time detect API. Differences are all
TPU-motivated:

* State lives on device as `ops.history.VersionHistory`; each batch is one
  jitted call (`ops.conflict.resolve_batch`) with donated state buffers —
  committed writes merge into the single-tier history inside the same
  call (no separate compaction step).
* Versions are rebased to int32 offsets of `base_version`; the rebase
  shifts every stored offset on device when the window drifts too far.
* Capacity overflow is latched on device and surfaced in every
  BatchVerdict; `resolve()` checks it on the same sync that reads the
  verdicts, so no decision computed against a truncated history is ever
  externalized. The async `resolve_packed` path (bench) checks every
  OVERFLOW_CHECK_INTERVAL batches to preserve pipelining.

The conflicting-key report follows the reference's recording order:
history-phase hits record every conflicting read-range index in
begin-key order (ranges are scanned sorted — SkipList.cpp:83,942), while
the intra-batch phase records only the first hit in range order and only
for txns the history phase didn't already condemn (:880-899).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.types import CommitTransaction, TransactionResult
from foundationdb_tpu.ops import conflict as C
from foundationdb_tpu.ops import history as H
from foundationdb_tpu.utils import packing

# Rebase when offsets pass 2**30 (window is ~5e6; huge safety margin).
REBASE_THRESHOLD = 1 << 30


class HistoryOverflowError(RuntimeError):
    """Compacted history exceeded `history_capacity`.

    The reference's skip list grows without bound inside the MVCC window;
    our capacity is static. Overflow means the config is undersized for
    the write rate x window product — a config error, never silent
    wrong answers.
    """


@dataclasses.dataclass
class BatchResult:
    verdicts: list[TransactionResult]
    conflicting_key_ranges: dict[int, list[int]]


def _rebase(state: H.VersionHistory, delta):
    """Shift every stored version offset down by delta (device-side)."""
    d = jnp.int32(delta)

    def shift(v):
        return jnp.where(v == H.VERSION_NEG, v, jnp.maximum(v - d, H.VERSION_NEG + 1))

    return state._replace(
        main_ver=shift(state.main_ver),
        oldest=shift(state.oldest),
    )


def _resolve_scan(state, stacked):
    """Resolve K stacked batches in ONE device program (lax.scan).

    Semantically identical to K sequential resolve_batch calls — the
    scan carry is the history state, so batch i+1 sees batch i's merged
    writes. One dispatch instead of K: through this environment's device
    tunnel a dispatch costs ~30ms, a third of the kernel itself
    (scripts/profile_serialized.py), and a loaded resolver coalescing
    its queue is exactly how the reference behaves under backpressure
    (fdbserver/Resolver.actor.cpp resolveBatch queueing).
    """

    def body(st, batch):
        st2, out = C.resolve_batch(st, batch)
        return st2, out

    return jax.lax.scan(body, state, stacked)


# Module-level jitted kernels: shared across all TpuConflictSet instances
# so N resolvers with the same KernelConfig compile once, not N times.
# State is deliberately NOT donated to the group kernel: the mega-sort
# gathers against the history buffers, and gathers from donated/carried
# buffers measure ~2x slower than from plain arguments on v5e
# (scripts/price_primitives.py); the un-donated copy is 2 x ~12MB.
from foundationdb_tpu.ops import group as _G

_RESOLVE = jax.jit(C.resolve_batch)
_RESOLVE_SCAN = jax.jit(_resolve_scan, donate_argnums=0)
_REBASE = jax.jit(_rebase, donate_argnums=0)

_GROUP_JITS: dict = {}


def _resolve_group_jit(short_span_limit: int, fixpoint_unroll: int = 3,
                       fixpoint_latch: bool = False):
    """One compiled group kernel per (short_span_limit, fixpoint_unroll,
    fixpoint_latch) triple (static compile-time switches — see
    ops/group.resolve_group)."""
    key = (short_span_limit, fixpoint_unroll, fixpoint_latch)
    fn = _GROUP_JITS.get(key)
    if fn is None:
        import functools

        fn = jax.jit(functools.partial(
            _G.resolve_group, short_span_limit=short_span_limit,
            fixpoint_unroll=fixpoint_unroll,
            fixpoint_latch=fixpoint_latch,
        ))
        _GROUP_JITS[key] = fn
    return fn

#: Overflow is checked host-side every this many batches (each check
#: forces a device sync; the merge itself is async).
OVERFLOW_CHECK_INTERVAL = 32


class TpuConflictSet:
    """Batch MVCC conflict detection with device-resident history."""

    def __init__(self, config: KernelConfig, base_version: int = 0):
        self.config = config
        self.base_version = base_version
        # Guard the production path against the known large-m flattened
        # gather miscompile class before the first decision is served
        # (ADVICE r3). Once per (platform, m) per process; XLA:CPU never
        # exhibited the bug and the sim/test lanes run there, so the
        # check is accelerator-only.
        from foundationdb_tpu.ops import rangemax as _rm

        if jax.default_backend() != "cpu":
            _rm.flat_gather_selftest(config.history_capacity)
        self.state = H.init(config)
        self._batches_since_check = 0
        self._resolve = _RESOLVE
        self._rebase = _REBASE

    # -- ConflictBatch-equivalent API -----------------------------------

    def resolve(
        self, transactions: list[CommitTransaction], version: int
    ) -> BatchResult:
        """Detect conflicts for one batch committing at `version`.

        Equivalent to addTransaction xN + detectConflicts
        (fdbserver/Resolver.actor.cpp:330-345): returns per-txn verdicts
        and the conflicting-key-range report, and merges committed writes
        into history at `version`.
        """
        if version - self.base_version > REBASE_THRESHOLD:
            delta = version - self.base_version - (1 << 20)
            self.state = self._rebase(self.state, np.int32(delta))
            self.base_version += delta

        batch = packing.pack_batch(
            transactions, version, self.base_version, self.config
        )
        self.state, out = self._resolve(self.state, batch.device_args())
        return self._build_result(transactions, batch, out)

    def _raise_overflow(self) -> None:
        self._batches_since_check = 0
        raise HistoryOverflowError(
            f"history_capacity={self.config.history_capacity} exceeded; "
            "increase it (or lower the MVCC window / write rate)"
        )

    def resolve_packed(self, batch: packing.PackedBatch) -> C.BatchVerdict:
        """Kernel-only path for pre-packed batches (bench / perf tests).

        Skips the Python packer and reply assembly; the caller owns
        version rebasing (offsets must fit int32).
        """
        return self.resolve_args(batch.device_args())

    def resolve_args(self, args) -> C.BatchVerdict:
        """Kernel-only path for an already-materialized device_args tree
        (host numpy or device-resident arrays alike)."""
        self.state, out = self._resolve(self.state, args)
        self._maybe_check_overflow()
        return out

    def resolve_args_scan(self, stacked_args) -> C.BatchVerdict:
        """Resolve K batches stacked on a leading axis in one dispatch.

        stacked_args: a device_args tree whose leaves carry a leading
        [K] axis. Returns a BatchVerdict with [K, ...] leaves, in batch
        order. State chains across the K batches inside the program.
        """
        self.state, outs = _RESOLVE_SCAN(self.state, stacked_args)
        self._batches_since_check += int(
            outs.verdict.shape[0]) - 1
        self._maybe_check_overflow()
        return outs

    def resolve_group_args(self, stacked_args, check_latch: bool = True):
        """Resolve K stacked batches via the GROUP kernel (ops/group.py):
        one mega-sort program instead of a lax.scan of per-batch
        kernels — same decisions (tests/test_group_parity.py), one
        dispatch, and the per-batch history merge amortized across the
        group. Versions must ascend across the stack (sequencer
        contract); a stale host-side check guards the bench path.

        With `config.fixpoint_latch` the latched kernel may REFUSE a
        group whose conflict chains run deeper than `fixpoint_unroll`
        (GroupVerdict.unconverged; the returned state is the unchanged
        input state). By default this method honors the kernel contract
        itself: it host-checks the latch and re-dispatches the same args
        on the exact while-loop kernel (ADVICE r4 — callers must never
        see untrustworthy verdicts). The check costs one device sync per
        group; pipelined callers that fence once per stream (bench.py)
        pass check_latch=False and fall back themselves. Call
        `prewarm_exact` up front so the fallback swaps programs in
        milliseconds instead of paying an XLA compile mid-stream.
        """
        ssl = getattr(self.config, "short_span_limit", 0)
        unroll = getattr(self.config, "fixpoint_unroll", 3)
        latch = getattr(self.config, "fixpoint_latch", False)
        state2, outs = _resolve_group_jit(ssl, unroll, latch)(
            self.state, stacked_args
        )
        if latch and check_latch and bool(np.asarray(outs.unconverged).any()):
            state2, outs = _resolve_group_jit(ssl, unroll, False)(
                self.state, stacked_args
            )
        self.state = state2
        self._batches_since_check += int(outs.verdict.shape[0]) - 1
        self._maybe_check_overflow()
        return outs

    def resolve_group_stream(self, host_groups: list,
                             check_latch: bool = True) -> list:
        """Resolve a stream of stacked groups with DOUBLE-BUFFERED
        staging: the host->device copy of group g+1 is issued before
        group g's compute is consumed, so transfer overlaps compute
        (VERDICT r4 task 4 — the reference's pipeline-overlap
        discipline, CommitProxyServer.actor.cpp:822-853). jax.device_put
        is asynchronous: the copy rides its own stream while the device
        crunches the previous group. Returns the GroupVerdicts in order;
        the caller fences (reads verdicts) when it consumes them."""
        if not host_groups:
            return []
        staged = jax.device_put(host_groups[0])
        outs = []
        for i in range(len(host_groups)):
            nxt = (
                jax.device_put(host_groups[i + 1])
                if i + 1 < len(host_groups) else None
            )
            outs.append(
                self.resolve_group_args(staged, check_latch=check_latch)
            )
            staged = nxt
        return outs

    def prewarm_exact(self, stacked_args) -> None:
        """Warm the exact while-loop group kernel for this args shape so
        a fixpoint-latch trip swaps programs in milliseconds instead of
        stalling the version chain behind an XLA compile — the reference
        resolver never stalls its chain (fdbserver/Resolver.actor.cpp:
        283-296). The group kernel does not donate state, so executing
        it once and discarding the results is side-effect-free; the
        compile lands in both the jit call cache and the persistent
        compile cache. No-op when fixpoint_latch is off."""
        if not getattr(self.config, "fixpoint_latch", False):
            return
        ssl = getattr(self.config, "short_span_limit", 0)
        unroll = getattr(self.config, "fixpoint_unroll", 3)
        _, outs = _resolve_group_jit(ssl, unroll, False)(
            self.state, stacked_args
        )
        jax.block_until_ready(outs.verdict)

    def _maybe_check_overflow(self) -> None:
        self._batches_since_check += 1
        if self._batches_since_check >= OVERFLOW_CHECK_INTERVAL:
            self.check_overflow()

    def check_overflow(self) -> None:
        """Device sync: raise if a merge ever exceeded history_capacity."""
        self._batches_since_check = 0
        if bool(np.asarray(self.state.overflow)):
            self._raise_overflow()

    # -- reply assembly --------------------------------------------------

    def _build_result(self, transactions, batch, out: C.BatchVerdict) -> BatchResult:
        n = len(transactions)
        verdict = np.asarray(out.verdict)[:n]
        # Same device sync the verdict read just paid: refuse to externalize
        # decisions computed against a truncated history (ADVICE r1 — the
        # interval-based check is only for the async packed path).
        if bool(np.asarray(out.overflow)):
            self._raise_overflow()
        hist_read = np.asarray(out.hist_conflict_read)
        intra_first = np.asarray(out.intra_first_range)[:n]
        verdicts = [TransactionResult(int(v)) for v in verdict]

        conflicting: dict[int, list[int]] = {}
        # group per-read-range history hits by txn
        hist_hits_by_txn: dict[int, list[tuple[bytes, int]]] = {}
        for r in range(batch.n_reads):
            if hist_read[r]:
                t = int(batch.read_txn[r])
                idx = int(batch.read_index[r])
                begin = transactions[t].read_conflict_ranges[idx][0]
                hist_hits_by_txn.setdefault(t, []).append((begin, idx))
        for t, tr in enumerate(transactions):
            if not tr.report_conflicting_keys:
                continue
            if verdicts[t] != TransactionResult.CONFLICT:
                continue
            if t in hist_hits_by_txn:
                hits = sorted(hist_hits_by_txn[t])  # begin-key order
                conflicting[t] = [i for _, i in hits]
            elif intra_first[t] >= 0:
                conflicting[t] = [int(intra_first[t])]
        return BatchResult(verdicts=verdicts, conflicting_key_ranges=conflicting)


class CpuConflictSet:
    """CPU fallback behind the resolver_backend knob: the same
    ConflictBatch interface served by the exact host-side semantic model
    (testing.oracle.ConflictOracle — the reference's SkipList semantics
    without a device). Mirrors BASELINE.json's contract that the CPU
    path stays available (`resolver_backend=cpu`), e.g. for
    deterministic simulation without device calls."""

    def __init__(self, config: KernelConfig, base_version: int = 0):
        from foundationdb_tpu.testing.oracle import ConflictOracle, OracleTxn

        self.config = config
        self._oracle_txn = OracleTxn
        self._oracle = ConflictOracle(window=config.window_versions)

    def resolve(
        self, transactions: list[CommitTransaction], version: int
    ) -> BatchResult:
        res = self._oracle.resolve(
            [
                self._oracle_txn(
                    t.read_conflict_ranges,
                    t.write_conflict_ranges,
                    t.read_snapshot,
                    t.report_conflicting_keys,
                )
                for t in transactions
            ],
            version,
        )
        verdicts = [TransactionResult(v) for v in res.verdicts]
        conflicting = {
            t: idxs
            for t, idxs in res.conflicting_ranges.items()
            if transactions[t].report_conflicting_keys
            and verdicts[t] == TransactionResult.CONFLICT
        }
        return BatchResult(verdicts=verdicts, conflicting_key_ranges=conflicting)

    def check_overflow(self) -> None:
        pass  # unbounded host memory


def make_conflict_set(config: KernelConfig, backend: str = None):
    """The resolver_backend knob gate (BASELINE.json: the TPU path sits
    behind a knob; the CPU path remains selectable).

    With backend "tpu", configs whose batch capacity sits under
    SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH auto-route to the CPU backend:
    at small batches the device dispatch alone exceeds the CPU's whole
    resolve (measured — bench.py BENCH_SMALL=1), so the TPU serves the
    loaded/batched regime and the CPU the latency regime. Explicit
    backend="tpu-force" bypasses the threshold (benches, tests)."""
    if backend is None:
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        backend = SERVER_KNOBS.RESOLVER_BACKEND
    if backend == "tpu":
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        if config.max_txns < SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH:
            # Loud reroute (ADVICE r4): the default KernelConfig sizes
            # max_txns at 1024, well under the measured device/CPU
            # crossover, so backend="tpu" quietly serving CPU would be
            # a silent surprise. The gate is on the config's static
            # batch CAPACITY — the kernel is compiled for max_txns, so
            # capacity bounds the largest batch this instance could
            # ever route and is the honest static proxy for load.
            from foundationdb_tpu.utils.trace import SEV_WARN, TraceEvent

            TraceEvent(
                "ResolverBackendAutoRouted", severity=SEV_WARN
            ).detail("Requested", "tpu").detail("Chosen", "cpu").detail(
                "MaxTxns", config.max_txns
            ).detail(
                "MinBatch", SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH
            ).log()
            return CpuConflictSet(config)
        return TpuConflictSet(config)
    if backend == "tpu-force":
        return TpuConflictSet(config)
    if backend == "cpu":
        return CpuConflictSet(config)
    raise ValueError(f"unknown resolver_backend {backend!r}")


# ---------------------------------------------------------------------------
# Contention-profile routing (VERDICT r4 task 2): batch size alone does
# not predict which backend wins — the r5 device measurements on the
# three graded configs (bench.py BENCH_MODE=*, logs *_r5.log) are:
#
#   uniform 1M keyspace:        device 0.70-0.97M vs skiplist ~0.31M (wins 2-3x)
#   zipf hot-key contention:    device 0.72M vs skiplist 1.07M  (LOSES, 0.68x)
#   range-heavy (500-key scans): device 0.59M vs skiplist 2.10M (LOSES, 0.28x)
#
# The CPU skiplist thrives exactly where the TPU kernel's fixed-width
# data-parallel passes cannot early-out: hot-key streams (conflict
# chains deepen, most txns abort fast on CPU) and wide scans (the
# skiplist skips subtrees; the kernel pays every covered block). Both
# regimes are CHEAPLY detectable host-side from the packed batch.


def profile_batch(batch, sample: int = 2048) -> str:
    """Classify a PackedBatch's contention regime: "uniform" |
    "hot_key" | "range_heavy". Host-side, O(sample)."""
    import numpy as np

    nw = max(1, batch.n_writes)
    nr = max(1, batch.n_reads)

    def key64(arr, n, j=None):
        # fold the first VARYING data word and its successor into one
        # int64: keyspaces with a common prefix (subspaces, short keys)
        # keep leading words constant, and folding constants would
        # collapse every key to one value (a spurious "hot_key")
        a = arr[: min(n, sample)].astype(np.int64)
        data = a[:, :-1] if a.shape[1] > 1 else a
        ncol = data.shape[1]
        if j is None:
            j = 0
            while j < ncol - 1 and len(np.unique(data[:, j])) == 1:
                j += 1
        if j + 1 < ncol:
            hi, lo = data[:, j], data[:, j + 1]
        else:
            # the varying word is the LAST one: it must occupy the LOW
            # slot or every span/dup scales by 2^32
            hi, lo = np.zeros(len(data), np.int64), data[:, j]
        return (hi << 32) | lo, j

    ws, _ = key64(batch.write_begin, nw)
    # duplicate-write-key rate in the sample (hot-key contention):
    # zipf-0.99 over 10M keys measures ~0.5+; uniform 64K/1M ~0.03
    dup = 1.0 - len(np.unique(ws)) / max(1, len(ws))
    if dup > 0.25:
        return "hot_key"
    rb, j = key64(batch.read_begin, nr)
    re, _ = key64(batch.read_end, nr, j)
    # mean span of read ranges in keyspace units: point reads span ~1;
    # the range-heavy config's scans span hundreds
    span = float(np.mean(np.minimum(np.maximum(re - rb, 0), 1 << 20)))
    if span > 32:
        return "range_heavy"
    return "uniform"


def profile_transactions(txns, sample: int = 512) -> str:
    """profile_batch for raw CommitTransaction lists (the resolver's
    input shape). Host-side, O(sample)."""
    import os

    writes = [
        r[0] for t in txns[:sample] for r in t.write_conflict_ranges
    ][:sample]
    if len(writes) >= 16:
        dup = 1.0 - len(set(writes)) / len(writes)
        if dup > 0.25:
            return "hot_key"
    reads = [
        r for t in txns[:sample] for r in t.read_conflict_ranges
    ][:sample]
    if reads:
        pref = len(os.path.commonprefix([b for b, _ in reads]))

        def as_int(x: bytes) -> int:
            return int.from_bytes(x[pref:pref + 8].ljust(8, b"\0"), "big")

        spans = [max(0, as_int(e) - as_int(b)) for b, e in reads]
        if sum(spans) / len(spans) > 32:
            return "range_heavy"
    return "uniform"


def backend_for_profile(profile: str) -> str:
    """The measured winner per regime (table above)."""
    return "tpu" if profile == "uniform" else "cpu"


def route_stream(batches, config, sample_batches: int = 2) -> str:
    """Pick the backend for a stream from its leading batches' profiles
    + the batch-capacity gate (RESOLVER_TPU_MIN_BATCH): TPU only for
    large-batch uniform streams — everything else is a measured CPU
    win. Used by the resolver role when resolver_backend="tpu"."""
    from foundationdb_tpu.utils.knobs import SERVER_KNOBS

    if config.max_txns < SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH:
        return "cpu"
    profiles = [profile_batch(b) for b in batches[:sample_batches]]
    if all(p == "uniform" for p in profiles):
        return "tpu"
    return "cpu"
