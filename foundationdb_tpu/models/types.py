"""Wire-level types of the resolution protocol.

Behavioral mirrors of the reference wire structs — same fields, same
semantics — so a host RPC layer can speak the same protocol:

* CommitTransaction ~ CommitTransactionRef
  (fdbclient/include/fdbclient/CommitTransaction.h:378-…): read/write
  conflict ranges, read_snapshot, report_conflicting_keys.
* ResolveTransactionBatchRequest / Reply ~
  fdbserver/include/fdbserver/ResolverInterface.h:94-155: the version
  chain fields (prevVersion, version, lastReceivedVersion) and the
  per-txn committed verdict array plus conflictingKeyRangeMap.

Mutations/state-transaction plumbing is carried opaquely (this framework's
scope is conflict resolution; the tlog/storage side consumes `mutations`
untouched).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional


class TransactionResult(enum.IntEnum):
    """Mirrors ConflictBatch::TransactionCommitResult
    (fdbserver/include/fdbserver/ConflictSet.h:41-46)."""

    CONFLICT = 0
    TOO_OLD = 1
    TENANT_FAILURE = 2
    COMMITTED = 3


KeyRange = tuple[bytes, bytes]


@dataclasses.dataclass
class CommitTransaction:
    read_conflict_ranges: list[KeyRange] = dataclasses.field(default_factory=list)
    write_conflict_ranges: list[KeyRange] = dataclasses.field(default_factory=list)
    read_snapshot: int = 0
    report_conflicting_keys: bool = False
    mutations: list[Any] = dataclasses.field(default_factory=list)
    # commit is allowed while the database is locked (the reference's
    # lock_aware transaction option; DR agents use it)
    lock_aware: bool = False
    # commit-path telemetry (CommitTransactionRequest's debugID +
    # spanContext): the per-transaction trace id the proxy attaches to
    # its batch id, and the client span context the batch span parents
    debug_id: Optional[str] = None
    span: Optional[tuple] = None

    def validate(self) -> None:
        for b, e in self.read_conflict_ranges + self.write_conflict_ranges:
            if not (isinstance(b, bytes) and isinstance(e, bytes)):
                raise TypeError("conflict range keys must be bytes")
            if b >= e:
                raise ValueError(f"empty conflict range {b!r} >= {e!r}")


@dataclasses.dataclass
class ResolveTransactionBatchRequest:
    prev_version: int          # -1 for the first batch (from the master)
    version: int               # commit version of this batch
    last_received_version: int  # acks outstanding replies below this
    transactions: list[CommitTransaction] = dataclasses.field(default_factory=list)
    # Indices into `transactions` that are metadata ("state") transactions;
    # their mutations are forwarded to every proxy via reply.state_mutations
    # (ResolverInterface.h:103 txnStateTransactions).
    txn_state_transactions: list[int] = dataclasses.field(default_factory=list)
    proxy_id: Optional[str] = None  # stands in for the reply endpoint address
    debug_id: Optional[str] = None
    # Generation fencing (the wire-cluster lifecycle): the proxy
    # generation's recovery epoch. A resolver serving generation E
    # rejects any batch whose epoch differs with a retryable
    # stale-epoch error (cluster/generation.py) — pre-recovery traffic
    # is fenced by epoch, not by luck. 0 = unfenced (standalone/sim
    # deployments without a cluster controller).
    epoch: int = 0
    # OTEL-style span context (trace_id, span_id) — the reference threads
    # a SpanContext on every request (ResolverInterface.h:129)
    span: Optional[tuple] = None
    # Storage tags written by this batch, proxy-computed from the shard
    # map (ResolverInterface.h:139 writtenTags; feeds the version-vector
    # tpcvMap path when ENABLE_VERSION_VECTOR_TLOG_UNICAST is on).
    written_tags: frozenset = frozenset()


@dataclasses.dataclass
class ResolveTransactionBatchReply:
    committed: list[TransactionResult] = dataclasses.field(default_factory=list)
    # txn index -> read-conflict-range indices (only for txns that asked)
    conflicting_key_range_map: dict[int, list[int]] = dataclasses.field(
        default_factory=dict
    )
    # Prior-version state transactions the requesting proxy hasn't seen
    # (ResolverInterface.h:141 stateMutations).
    state_mutations: list[Any] = dataclasses.field(default_factory=list)
    # Knob-gated (PROXY_USE_RESOLVER_PRIVATE_MUTATIONS): THIS batch's
    # candidate metadata mutations per LOCAL txn index, generated
    # resolver-side (ResolverInterface.h:143 privateMutations;
    # Resolver.actor.cpp:372-441). Candidates carry the resolver-LOCAL
    # committed verdict; the proxy applies only those whose GLOBAL
    # (min-combined) verdict is committed — global committed implies
    # locally committed everywhere, so candidates are complete. Empty
    # when the knob is off.
    private_mutations: dict[int, list[Any]] = dataclasses.field(
        default_factory=dict
    )
    debug_id: Optional[str] = None
    # Version-vector surface (knob ENABLE_VERSION_VECTOR_TLOG_UNICAST;
    # ResolverInterface.h:140-151 + Resolver.actor.cpp:475-495): per
    # written tlog, the PREVIOUS commit version that touched it — what
    # lets tlogs chain unicast pushes without hearing every version.
    # Empty when the knob is off.
    tpcv_map: dict[int, int] = dataclasses.field(default_factory=dict)
    written_tags: frozenset = frozenset()


#: the \xff system keyspace prefix (fdbclient/SystemData.cpp)
SYSTEM_PREFIX = b"\xff"


def is_metadata_mutation(m) -> bool:
    """Metadata mutations target the system keyspace — the
    applyMetadataToCommittedTransactions condition
    (fdbserver/CommitProxyServer.actor.cpp:1596)."""
    key = m[2] if m[0] == "atomic" else m[1]
    return key.startswith(SYSTEM_PREFIX)


def apply_state_mutation(store: dict, m) -> None:
    """Apply one metadata mutation to a txn-state store dict — shared by
    the cluster-side store (cluster/database.py) and the resolver-side
    materialization (the private-mutations path), so the two can never
    drift in semantics."""
    kind = m[0]
    if kind == "set":
        store[m[1]] = m[2]
    elif kind == "clear":
        for k in [k for k in store if m[1] <= k < m[2]]:
            del store[k]
