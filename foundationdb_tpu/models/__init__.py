"""Role models: wire types, the conflict set, and the resolver role."""
