"""Host-side batch packing: byte-string conflict ranges -> fixed-shape tensors.

This is the boundary where a `ResolveTransactionBatchRequest`'s
variable-length data (reference wire type:
fdbserver/include/fdbserver/ResolverInterface.h:94-129) becomes the packed,
static-shape tensors the TPU kernel consumes. Reads and writes are packed
*flat* (one row per conflict range, with a txn-id column) rather than
[B, R, ...] so that sparse per-txn range counts don't waste device FLOPs.

Versions are rebased to int32 offsets from a host-held base version: the
MVCC window is ~5e6 versions (fdbclient/ServerKnobs.cpp:43) so every live
version fits comfortably in 31 bits; `Resolver` re-bases periodically.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from foundationdb_tpu.config import KernelConfig

# Version offset used for "far in the past" (clamped stale snapshots).
VERSION_NEG = np.int32(-(2**31) + 1)


class KeyTooLongError(ValueError):
    """Kept for API compatibility; the packer no longer raises it."""


def pack_key(key: bytes, max_key_bytes: int, *, round_up: bool = False) -> np.ndarray:
    """bytes -> [W] uint32 (big-endian byte words + length word).

    Keys longer than max_key_bytes degrade CONSERVATIVELY (SURVEY.md §7.3
    names exact long-key order the #1 parity risk): a truncated begin key
    keeps length == max (sorts at-or-before the original), a truncated
    end key gets length max+1 — "just past every key with this prefix" —
    so it sorts after them. Ranges only ever EXPAND, which can add
    spurious conflicts for >max-byte keys but can never miss one.
    """
    if len(key) > max_key_bytes:
        length = max_key_bytes + 1 if round_up else max_key_bytes
        key = key[:max_key_bytes]
    else:
        length = len(key)
    padded = key + b"\x00" * (max_key_bytes - len(key))
    words = np.frombuffer(padded, dtype=">u4").astype(np.uint32)
    return np.concatenate([words, np.array([length], np.uint32)])


def pack_keys(
    keys: list[bytes], max_key_bytes: int, *, round_up: bool = False
) -> np.ndarray:
    """[n, W] uint32; vectorized over a list of byte keys (see pack_key
    for the conservative long-key handling).

    Bulk-numpy formulation (r6): ONE joined byte blob scattered into the
    padded matrix through cumsum offsets, instead of a per-key
    frombuffer loop — the loop dominated host packing at bench batch
    sizes (tests/test_packing.py pins byte-identical output against the
    loop version, _pack_keys_reference). The scatter itself lives in
    pack_keys_from_blob so the columnar wire decode (r12) runs the SAME
    code over the frame's already-joined blob — the two paths cannot
    produce different matrices.
    """
    n = len(keys)
    w = max_key_bytes // 4 + 1
    if n == 0:
        return np.zeros((n, w), np.uint32)
    lens = np.fromiter((len(k) for k in keys), np.int64, count=n)
    cat = np.frombuffer(b"".join(keys), np.uint8)
    return pack_keys_from_blob(
        cat, np.cumsum(lens) - lens, lens, max_key_bytes, round_up=round_up
    )


def pack_keys_from_blob(
    cat: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    max_key_bytes: int,
    *,
    round_up: bool = False,
) -> np.ndarray:
    """pack_keys over an already-joined key blob: key i occupies
    ``cat[starts[i] : starts[i] + lens[i]]`` (uint8 view, possibly a
    zero-copy view of a wire frame payload).

    This is the columnar resolve frame's decode-to-kernel scatter —
    and the body pack_keys itself delegates to, so the object path and
    the columnar path are byte-identical by construction, not by test
    alone. Long keys (> max_key_bytes) degrade conservatively exactly
    like pack_key: only the first max_key_bytes bytes are taken and the
    length word saturates (max, or max+1 for round_up end keys).
    """
    n = len(lens)
    w = max_key_bytes // 4 + 1
    out = np.zeros((n, w), np.uint32)
    if n == 0:
        return out
    lens = np.asarray(lens, np.int64)
    starts = np.asarray(starts, np.int64)
    over = lens > max_key_bytes
    kept = np.minimum(lens, max_key_bytes)
    out_lens = np.where(
        over, max_key_bytes + 1 if round_up else max_key_bytes, lens
    )
    buf = np.zeros((n, max_key_bytes), np.uint8)
    rows = np.repeat(np.arange(n), kept)
    offs = np.cumsum(kept) - kept
    cols = np.arange(int(kept.sum())) - np.repeat(offs, kept)
    buf[rows, cols] = cat[np.repeat(starts, kept) + cols]
    out[:, :-1] = buf.view(">u4").astype(np.uint32).reshape(n, w - 1)
    out[:, -1] = out_lens.astype(np.uint32)
    return out


def _pack_keys_reference(
    keys: list[bytes], max_key_bytes: int, *, round_up: bool = False
) -> np.ndarray:
    """The pre-r6 per-key loop packer, kept as the byte-identical
    regression oracle for the vectorized pack_keys (tests/test_packing)."""
    n = len(keys)
    w = max_key_bytes // 4 + 1
    out = np.zeros((n, w), np.uint32)
    if n == 0:
        return out
    buf = np.zeros((n, max_key_bytes), np.uint8)
    lens = np.empty((n,), np.uint32)
    for i, k in enumerate(keys):
        if len(k) > max_key_bytes:
            lens[i] = max_key_bytes + 1 if round_up else max_key_bytes
            k = k[:max_key_bytes]
        else:
            lens[i] = len(k)
        buf[i, : len(k)] = np.frombuffer(k, np.uint8)
    out[:, :-1] = buf.view(">u4").astype(np.uint32).reshape(n, w - 1)
    out[:, -1] = lens
    return out


def unpack_key(row: np.ndarray) -> bytes:
    """[W] uint32 -> bytes (inverse of pack_key)."""
    length = int(row[-1])
    raw = row[:-1].astype(">u4").tobytes()
    return raw[:length]


@dataclasses.dataclass
class PackedBatch:
    """One batch of transactions in kernel form (all numpy, host-side).

    Shapes are exactly the KernelConfig caps; `n_txns`/`n_reads`/`n_writes`
    give the live prefix sizes (rows past them are masked invalid).
    """

    # scalars
    version: np.int32          # commit version offset of this batch
    new_oldest: np.int32       # MVCC-window floor offset (version - window)
    n_txns: int
    n_reads: int
    n_writes: int
    # per-txn [B]
    txn_valid: np.ndarray      # bool
    snapshot: np.ndarray       # int32 version offsets (clamped at VERSION_NEG)
    has_reads: np.ndarray      # bool — blind writes are never "too old"
    # flattened reads [NR]
    read_begin: np.ndarray     # [NR, W] uint32
    read_end: np.ndarray       # [NR, W] uint32
    read_txn: np.ndarray       # int32
    read_index: np.ndarray     # int32 — index of the range within its txn
    read_valid: np.ndarray     # bool
    # flattened writes [NW]
    write_begin: np.ndarray    # [NW, W] uint32
    write_end: np.ndarray      # [NW, W] uint32
    write_txn: np.ndarray      # int32
    write_valid: np.ndarray    # bool

    def device_args(self):
        """The pytree handed to the jitted kernel (drops host-only ints)."""
        return {
            "version": np.int32(self.version),
            "new_oldest": np.int32(self.new_oldest),
            "txn_valid": self.txn_valid,
            "snapshot": self.snapshot,
            "has_reads": self.has_reads,
            "read_begin": self.read_begin,
            "read_end": self.read_end,
            "read_txn": self.read_txn,
            "read_index": self.read_index,
            "read_valid": self.read_valid,
            "write_begin": self.write_begin,
            "write_end": self.write_end,
            "write_txn": self.write_txn,
            "write_valid": self.write_valid,
        }


def _clamp_version(v: int, base: int) -> np.int32:
    off = v - base
    if off <= int(VERSION_NEG):
        return VERSION_NEG
    if off >= 2**31:
        raise OverflowError(f"version offset {off} overflows int32; rebase")
    return np.int32(off)


def pack_batch(
    transactions,
    version: int,
    base_version: int,
    config: KernelConfig,
) -> PackedBatch:
    """Pack a list of CommitTransaction into kernel tensors.

    `transactions` is any sequence with `.read_conflict_ranges`,
    `.write_conflict_ranges` (lists of (begin, end) byte pairs) and
    `.read_snapshot` (int) — the shape of the reference's
    CommitTransactionRef (fdbclient/include/fdbclient/CommitTransaction.h).

    Bulk-numpy formulation (r6): per-txn columns come from
    repeat/cumsum over pre-flattened range lists instead of the pre-r6
    append loops, so host packing stops dominating the pipelined stream
    (the pack stage of TpuConflictSet.resolve_stream_pipelined).
    Byte-identical to pack_batch_reference (tests/test_packing.py).
    """
    cfg = config
    b, nr, nw, w = cfg.max_txns, cfg.max_reads, cfg.max_writes, cfg.key_words
    n = len(transactions)
    if n > b:
        raise ValueError(f"{n} txns > max_txns {b}")

    txn_valid = np.zeros((b,), bool)
    snapshot = np.full((b,), VERSION_NEG, np.int32)
    has_reads = np.zeros((b,), bool)
    r_lists = [tr.read_conflict_ranges for tr in transactions]
    w_lists = [tr.write_conflict_ranges for tr in transactions]
    if n:
        txn_valid[:n] = True
        off = np.fromiter(
            (tr.read_snapshot for tr in transactions), np.int64, count=n
        ) - base_version
        high = off >= 2**31
        if high.any():
            bad = int(off[high][0])
            raise OverflowError(f"version offset {bad} overflows int32; rebase")
        snapshot[:n] = np.where(
            off <= int(VERSION_NEG), int(VERSION_NEG), off
        ).astype(np.int32)
        r_counts = np.fromiter((len(x) for x in r_lists), np.int64, count=n)
        w_counts = np.fromiter((len(x) for x in w_lists), np.int64, count=n)
        has_reads[:n] = r_counts > 0
    else:
        r_counts = w_counts = np.zeros((0,), np.int64)

    nread = int(r_counts.sum())
    nwrite = int(w_counts.sum())
    if nread > nr:
        raise ValueError(f"{nread} read ranges > max_reads {nr}")
    if nwrite > nw:
        raise ValueError(f"{nwrite} write ranges > max_writes {nw}")

    r_flat = [rg for lst in r_lists for rg in lst]
    w_flat = [rg for lst in w_lists for rg in lst]
    ids = np.arange(n, dtype=np.int32)
    r_txn = np.repeat(ids, r_counts)
    w_txn = np.repeat(ids, w_counts)
    r_starts = np.concatenate([[0], np.cumsum(r_counts)[:-1]]) if n else r_counts
    r_idx = (np.arange(nread) - np.repeat(r_starts, r_counts)).astype(np.int32)

    def _flat_keys(pairs, cap):
        kb = np.zeros((cap, w), np.uint32)
        ke = np.zeros((cap, w), np.uint32)
        m = len(pairs)
        if m:
            kb[:m] = pack_keys([p[0] for p in pairs], cfg.max_key_bytes)
            ke[:m] = pack_keys(
                [p[1] for p in pairs], cfg.max_key_bytes, round_up=True
            )
        return kb, ke

    rb, re = _flat_keys(r_flat, nr)
    wb, we = _flat_keys(w_flat, nw)

    def _col(vals, cap, dtype=np.int32, fill=0):
        out = np.full((cap,), fill, dtype)
        out[: len(vals)] = vals
        return out
    return PackedBatch(
        version=_clamp_version(version, base_version),
        new_oldest=_clamp_version(version - cfg.window_versions, base_version),
        n_txns=len(transactions),
        n_reads=nread,
        n_writes=nwrite,
        txn_valid=txn_valid,
        snapshot=snapshot,
        has_reads=has_reads,
        read_begin=rb,
        read_end=re,
        # KERNEL LAYOUT CONTRACT (ops/group.py per-txn windows): rows are
        # grouped by txn in nondecreasing txn order with ranges in
        # declaration order, and PADDING rows carry txn id == max_txns —
        # the flat (batch, txn) segment id is then monotone, which lets
        # the kernel do per-txn reductions with cumsum windows instead
        # of scatters.
        read_txn=_col(r_txn, nr, fill=b),
        read_index=_col(r_idx, nr),
        read_valid=_col([True] * nread, nr, bool),
        write_begin=wb,
        write_end=we,
        write_txn=_col(w_txn, nw, fill=b),
        write_valid=_col([True] * nwrite, nw, bool),
    )


def pack_batch_reference(
    transactions,
    version: int,
    base_version: int,
    config: KernelConfig,
) -> PackedBatch:
    """The pre-r6 per-txn append-loop packer, kept verbatim as the
    byte-identical regression oracle for the vectorized pack_batch
    (tests/test_packing.py). Not on any hot path."""
    cfg = config
    b, nr, nw, w = cfg.max_txns, cfg.max_reads, cfg.max_writes, cfg.key_words
    if len(transactions) > b:
        raise ValueError(f"{len(transactions)} txns > max_txns {b}")

    txn_valid = np.zeros((b,), bool)
    snapshot = np.full((b,), VERSION_NEG, np.int32)
    has_reads = np.zeros((b,), bool)

    r_begin, r_end, r_txn, r_idx = [], [], [], []
    w_begin, w_end, w_txn = [], [], []
    for t, tr in enumerate(transactions):
        txn_valid[t] = True
        snapshot[t] = _clamp_version(tr.read_snapshot, base_version)
        has_reads[t] = len(tr.read_conflict_ranges) > 0
        for i, (kb, ke) in enumerate(tr.read_conflict_ranges):
            r_begin.append(kb)
            r_end.append(ke)
            r_txn.append(t)
            r_idx.append(i)
        for kb, ke in tr.write_conflict_ranges:
            w_begin.append(kb)
            w_end.append(ke)
            w_txn.append(t)

    if len(r_txn) > nr:
        raise ValueError(f"{len(r_txn)} read ranges > max_reads {nr}")
    if len(w_txn) > nw:
        raise ValueError(f"{len(w_txn)} write ranges > max_writes {nw}")

    def _flat(begins, ends, cap):
        kb = np.zeros((cap, w), np.uint32)
        ke = np.zeros((cap, w), np.uint32)
        n = len(begins)
        if n:
            kb[:n] = _pack_keys_reference(begins, cfg.max_key_bytes)
            ke[:n] = _pack_keys_reference(
                ends, cfg.max_key_bytes, round_up=True
            )
        return kb, ke

    rb, re = _flat(r_begin, r_end, nr)
    wb, we = _flat(w_begin, w_end, nw)

    def _col(vals, cap, dtype=np.int32, fill=0):
        out = np.full((cap,), fill, dtype)
        out[: len(vals)] = vals
        return out

    nread, nwrite = len(r_txn), len(w_txn)
    return PackedBatch(
        version=_clamp_version(version, base_version),
        new_oldest=_clamp_version(version - cfg.window_versions, base_version),
        n_txns=len(transactions),
        n_reads=nread,
        n_writes=nwrite,
        txn_valid=txn_valid,
        snapshot=snapshot,
        has_reads=has_reads,
        read_begin=rb,
        read_end=re,
        read_txn=_col(r_txn, nr, fill=b),
        read_index=_col(r_idx, nr),
        read_valid=_col([True] * nread, nr, bool),
        write_begin=wb,
        write_end=we,
        write_txn=_col(w_txn, nw, fill=b),
        write_valid=_col([True] * nwrite, nw, bool),
    )


# ---------------------------------------------------------------------------
# Columnar resolve batch (r12 — the wire-to-kernel path): one batch's
# conflict metadata as flat columns, packed ONCE at the proxy in the
# layout pack_batch already consumes (per-txn counts + one joined key
# blob + versions), so the resolver decodes wire bytes straight into
# kernel tensors without ever materializing per-transaction objects.

#: The columnar frame's array layout — ONE constant shared by the wire
#: encoder and decoder (wire/codec.py w_/r_resolve_columnar) so dtypes
#: and endianness can never drift: every column is a packed
#: little-endian fixed-width vector with NO padding or alignment (the
#: decoder reads with np.frombuffer at raw byte offsets; numpy handles
#: unaligned access). Array lengths derive from the frame header's
#: (n_txns, n_reads, n_writes) counts. The key blob follows as one
#: u32-length-prefixed contiguous slice.
COLUMNAR_LAYOUT = (
    ("snapshots", "<i8", "n_txns"),
    ("read_counts", "<u4", "n_txns"),
    ("write_counts", "<u4", "n_txns"),
    ("flags", "<u1", "n_txns"),
    ("key_lens", "<u4", "n_keys"),  # n_keys = 2*n_reads + 2*n_writes
)

#: flags bit 0: the txn asked for the conflicting-key-range report
COLUMNAR_FLAG_REPORT = 1

#: canonical key order inside key_lens / key_blob: all read-range begin
#: keys, then read ends, then write begins, then write ends — four
#: contiguous runs so each kernel column packs with ONE vectorized
#: scatter over its slice of the blob
_KEY_ORDER_DOC = ("read_begin", "read_end", "write_begin", "write_end")


@dataclasses.dataclass
class ColumnarBatch:
    """One resolve batch as flat columns (host side of the columnar
    wire frame; see COLUMNAR_LAYOUT for the wire dtypes).

    Versions are ABSOLUTE here (the proxy doesn't know the resolver's
    rebase base); pack_batch_columnar does the same vectorized
    offset/clamp pass pack_batch does. Keys are carried LOSSLESSLY in
    the blob — truncation of over-length keys happens only in the
    kernel packer, so the object-path fallback (native skip list / CPU
    oracle via columnar_to_transactions) sees exact bytes.
    """

    n_txns: int
    n_reads: int               # sum(read_counts) — cross-checked on decode
    n_writes: int              # sum(write_counts)
    snapshots: np.ndarray      # <i8 [n_txns] absolute read_snapshot
    read_counts: np.ndarray    # <u4 [n_txns]
    write_counts: np.ndarray   # <u4 [n_txns]
    flags: np.ndarray          # <u1 [n_txns] (COLUMNAR_FLAG_REPORT)
    key_lens: np.ndarray       # <u4 [2*n_reads + 2*n_writes], canonical order
    key_blob: Any              # bytes | memoryview, sum(key_lens) bytes

    def __eq__(self, other):
        if not isinstance(other, ColumnarBatch):
            return NotImplemented
        return (
            self.n_txns == other.n_txns
            and self.n_reads == other.n_reads
            and self.n_writes == other.n_writes
            and np.array_equal(self.snapshots, other.snapshots)
            and np.array_equal(self.read_counts, other.read_counts)
            and np.array_equal(self.write_counts, other.write_counts)
            and np.array_equal(self.flags, other.flags)
            and np.array_equal(self.key_lens, other.key_lens)
            and bytes(self.key_blob) == bytes(other.key_blob)
        )


def pack_columnar(transactions) -> ColumnarBatch:
    """Proxy-side columnar pack: CommitTransaction list -> flat columns,
    ONCE per batch at batch-build time (the per-key work is one bytes
    join; everything per-txn is bulk numpy). The resolver side never
    re-flattens: pack_batch_columnar scatters the blob straight into
    kernel tensors."""
    n = len(transactions)
    r_lists = [t.read_conflict_ranges for t in transactions]
    w_lists = [t.write_conflict_ranges for t in transactions]
    if n:
        read_counts = np.fromiter(
            (len(x) for x in r_lists), np.uint32, count=n
        )
        write_counts = np.fromiter(
            (len(x) for x in w_lists), np.uint32, count=n
        )
        snapshots = np.fromiter(
            (t.read_snapshot for t in transactions), np.int64, count=n
        )
        flags = np.fromiter(
            (
                COLUMNAR_FLAG_REPORT if t.report_conflicting_keys else 0
                for t in transactions
            ),
            np.uint8,
            count=n,
        )
    else:
        read_counts = write_counts = np.zeros((0,), np.uint32)
        snapshots = np.zeros((0,), np.int64)
        flags = np.zeros((0,), np.uint8)
    # canonical key order (_KEY_ORDER_DOC): four contiguous runs
    keys: list[bytes] = []
    for lists, side in ((r_lists, 0), (r_lists, 1), (w_lists, 0), (w_lists, 1)):
        keys.extend(rg[side] for lst in lists for rg in lst)
    nread, nwrite = int(read_counts.sum()), int(write_counts.sum())
    key_lens = (
        np.fromiter((len(k) for k in keys), np.uint32, count=len(keys))
        if keys
        else np.zeros((0,), np.uint32)
    )
    return ColumnarBatch(
        n_txns=n,
        n_reads=nread,
        n_writes=nwrite,
        snapshots=snapshots,
        read_counts=read_counts,
        write_counts=write_counts,
        flags=flags,
        key_lens=key_lens,
        key_blob=b"".join(keys),
    )


def pack_batch_columnar(
    cols: ColumnarBatch,
    version: int,
    base_version: int,
    config: KernelConfig,
) -> PackedBatch:
    """Columnar twin of pack_batch: flat columns -> kernel tensors.

    Byte-identical to ``pack_batch(txns, ...)`` whenever
    ``cols == pack_columnar(txns)`` (pinned in tests/test_packing.py)
    — the per-txn columns come from the same repeat/cumsum formulas and
    the key matrices from the same pack_keys_from_blob scatter, so the
    columnar wire path and the object path cannot diverge in what the
    kernel sees. No per-transaction Python objects are materialized.
    """
    cfg = config
    b, nr, nw, w = cfg.max_txns, cfg.max_reads, cfg.max_writes, cfg.key_words
    n = cols.n_txns
    if n > b:
        raise ValueError(f"{n} txns > max_txns {b}")

    txn_valid = np.zeros((b,), bool)
    snapshot = np.full((b,), VERSION_NEG, np.int32)
    has_reads = np.zeros((b,), bool)
    if n:
        txn_valid[:n] = True
        off = cols.snapshots.astype(np.int64) - base_version
        high = off >= 2**31
        if high.any():
            bad = int(off[high][0])
            raise OverflowError(f"version offset {bad} overflows int32; rebase")
        snapshot[:n] = np.where(
            off <= int(VERSION_NEG), int(VERSION_NEG), off
        ).astype(np.int32)
        r_counts = cols.read_counts.astype(np.int64)
        w_counts = cols.write_counts.astype(np.int64)
        has_reads[:n] = r_counts > 0
    else:
        r_counts = w_counts = np.zeros((0,), np.int64)

    nread = int(r_counts.sum())
    nwrite = int(w_counts.sum())
    if nread > nr:
        raise ValueError(f"{nread} read ranges > max_reads {nr}")
    if nwrite > nw:
        raise ValueError(f"{nwrite} write ranges > max_writes {nw}")

    ids = np.arange(n, dtype=np.int32)
    r_txn = np.repeat(ids, r_counts)
    w_txn = np.repeat(ids, w_counts)
    r_starts = np.cumsum(r_counts) - r_counts if n else r_counts
    r_idx = (np.arange(nread) - np.repeat(r_starts, r_counts)).astype(np.int32)

    cat = np.frombuffer(cols.key_blob, np.uint8)
    lens = np.asarray(cols.key_lens, np.int64)
    starts = np.cumsum(lens) - lens

    def _col_keys(lo, m, cap, round_up):
        out = np.zeros((cap, w), np.uint32)
        if m:
            out[:m] = pack_keys_from_blob(
                cat, starts[lo : lo + m], lens[lo : lo + m],
                cfg.max_key_bytes, round_up=round_up,
            )
        return out

    rb = _col_keys(0, nread, nr, False)
    re = _col_keys(nread, nread, nr, True)
    wb = _col_keys(2 * nread, nwrite, nw, False)
    we = _col_keys(2 * nread + nwrite, nwrite, nw, True)

    def _col(vals, cap, dtype=np.int32, fill=0):
        out = np.full((cap,), fill, dtype)
        out[: len(vals)] = vals
        return out

    return PackedBatch(
        version=_clamp_version(version, base_version),
        new_oldest=_clamp_version(version - cfg.window_versions, base_version),
        n_txns=n,
        n_reads=nread,
        n_writes=nwrite,
        txn_valid=txn_valid,
        snapshot=snapshot,
        has_reads=has_reads,
        read_begin=rb,
        read_end=re,
        read_txn=_col(r_txn, nr, fill=b),
        read_index=_col(r_idx, nr),
        read_valid=_col([True] * nread, nr, bool),
        write_begin=wb,
        write_end=we,
        write_txn=_col(w_txn, nw, fill=b),
        write_valid=_col([True] * nwrite, nw, bool),
    )


def columnar_key(cols: ColumnarBatch, index: int) -> bytes:
    """Key `index` (canonical order) sliced out of the blob — used by
    the conflicting-key-range report assembly, which only touches the
    (rare) rows the kernel flagged."""
    lens = cols.key_lens
    start = int(np.asarray(lens[:index], np.int64).sum())
    return bytes(
        memoryview(cols.key_blob)[start : start + int(lens[index])]
    )


def columnar_to_transactions(cols: ColumnarBatch) -> list:
    """Columnar frame -> per-txn CommitTransaction objects: the OBJECT
    fallback for conflict backends that consume byte keys directly (the
    native skip list, the CPU oracle). Keys are exact — the blob
    carries full bytes, truncation only ever happens in the kernel
    packer — so decisions match the object wire path bit for bit."""
    from foundationdb_tpu.models.types import CommitTransaction

    lens = np.asarray(cols.key_lens, np.int64)
    ends = np.cumsum(lens)
    starts = ends - lens
    view = memoryview(cols.key_blob)
    keys = [bytes(view[s:e]) for s, e in zip(starts, ends)]
    nread, nwrite = cols.n_reads, cols.n_writes
    rb, re_ = keys[:nread], keys[nread : 2 * nread]
    wb = keys[2 * nread : 2 * nread + nwrite]
    we = keys[2 * nread + nwrite :]
    out = []
    ri = wi = 0
    for t in range(cols.n_txns):
        rc = int(cols.read_counts[t])
        wc = int(cols.write_counts[t])
        out.append(
            CommitTransaction(
                read_conflict_ranges=list(
                    zip(rb[ri : ri + rc], re_[ri : ri + rc])
                ),
                write_conflict_ranges=list(
                    zip(wb[wi : wi + wc], we[wi : wi + wc])
                ),
                read_snapshot=int(cols.snapshots[t]),
                report_conflicting_keys=bool(
                    int(cols.flags[t]) & COLUMNAR_FLAG_REPORT
                ),
            )
        )
        ri += rc
        wi += wc
    return out


def stack_device_args(batches) -> dict:
    """Stack PackedBatch device_args along a new leading axis — the
    input contract of TpuConflictSet.resolve_args_scan. Single place so
    a new device_args key can never be silently dropped by callers."""
    import numpy as _np

    args = [b.device_args() for b in batches]
    versions = [int(a["version"]) for a in args]
    if any(b <= a for a, b in zip(versions, versions[1:])):
        # the group kernel's cross-batch visibility masks assume the
        # sequencer's monotone version order
        raise ValueError(f"stacked batch versions must ascend: {versions}")
    return {k: _np.stack([a[k] for a in args]) for k in args[0]}
