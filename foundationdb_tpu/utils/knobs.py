"""Knobs: typed runtime constants with randomize-under-test, plus BUGGIFY.

Behavioral mirror of the reference's knob system (`flow/Knobs.cpp`,
`fdbclient/ServerKnobs.cpp`): every tunable is a named, typed constant;
under simulation a seeded fraction of knobs take randomized values to
widen coverage (the `randomize && BUGGIFY` idiom, e.g.
ServerKnobs.cpp:43-44), and `buggify(...)` deterministically enables rare
code paths per seed (flow/include/flow/flow.h:63-81 BUGGIFY).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import numpy as np


@dataclasses.dataclass
class _KnobDef:
    name: str
    default: Any
    ktype: type
    randomize: Optional[Callable[[np.random.Generator], Any]] = None


class Knobs:
    """A named knob collection (FLOW_KNOBS / SERVER_KNOBS shape)."""

    def __init__(self, name: str):
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_defs", {})
        object.__setattr__(self, "_values", {})

    def define(self, name: str, default, *, randomize=None) -> None:
        d = _KnobDef(name, default, type(default), randomize)
        self._defs[name] = d
        self._values[name] = default

    def __getattr__(self, name: str):
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(f"unknown knob {name!r}") from None

    def __setattr__(self, name: str, value) -> None:
        self.set(name, value)

    def set(self, name: str, value) -> None:
        """--knob_<name>=<value> (type-checked against the default)."""
        if name not in self._defs:
            raise KeyError(f"unknown knob {name!r}")
        d = self._defs[name]
        if not isinstance(value, d.ktype):
            value = d.ktype(value)
        self._values[name] = value

    def reset(self) -> None:
        for n, d in self._defs.items():
            self._values[n] = d.default

    def randomize_under_test(self, rng: np.random.Generator, prob: float = 0.5):
        """Seeded knob randomization (ServerKnobs' randomize && BUGGIFY)."""
        chosen = {}
        for n, d in self._defs.items():
            if d.randomize is not None and rng.random() < prob:
                self._values[n] = chosen[n] = d.randomize(rng)
        return chosen

    def as_dict(self) -> dict:
        return dict(self._values)

    def apply_env_overrides(self, env_var: str = None) -> dict:
        """Apply `NAME=value;NAME=value` overrides from an environment
        variable (default FDBTPU_KNOB_OVERRIDES) — the hook the
        autotuner's subprocess harnesses use to drive knob trials
        (scripts/autotune.py sets it per trial; values are coerced via
        set()'s type check). Returns {name: value} of what was applied
        so harnesses can record the knob fingerprint honestly."""
        import os as _os

        raw = _os.environ.get(env_var or "FDBTPU_KNOB_OVERRIDES", "")
        applied = {}
        for part in raw.split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, value = part.partition("=")
            name, value = name.strip(), value.strip()
            d = self._defs.get(name)
            if d is not None and d.ktype is bool:
                # bool('False') is True — env strings need real
                # parsing, and an unrecognized spelling is a config
                # error, never a silent True
                lowered = value.lower()
                if lowered in ("1", "true", "yes", "on"):
                    parsed = True
                elif lowered in ("0", "false", "no", "off"):
                    parsed = False
                else:
                    raise ValueError(
                        f"knob {name!r}: {value!r} is not a boolean "
                        "(use true/false/1/0)"
                    )
                self.set(name, parsed)
            else:
                self.set(name, value)
            applied[name] = self._values[name]
        return applied


class Buggifier:
    """Deterministic rare-branch activation (BUGGIFY).

    Each call site (identified by its string tag) is enabled once per
    seed with `activation_prob`; enabled sites then fire with
    `fire_prob` per evaluation — the reference's two-level scheme
    (flow/flow.h:63-81: P_ENABLED per site, P_FIRE per hit).
    """

    def __init__(self, seed: int = 0, *, enabled: bool = False,
                 activation_prob: float = 0.25, fire_prob: float = 0.05):
        self.enabled = enabled
        self.activation_prob = activation_prob
        self.fire_prob = fire_prob
        self._rng = np.random.default_rng(seed)
        self._site_enabled: dict[str, bool] = {}

    def __call__(self, site: str) -> bool:
        if not self.enabled:
            return False
        if site not in self._site_enabled:
            self._site_enabled[site] = (
                float(self._rng.random()) < self.activation_prob
            )
        return self._site_enabled[site] and (
            float(self._rng.random()) < self.fire_prob
        )


#: Global buggifier — off outside simulation, like the reference's.
BUGGIFY = Buggifier()


def make_server_knobs() -> Knobs:
    """The resolver-relevant server knobs with reference defaults
    (fdbclient/ServerKnobs.cpp:36-44, 549-550 + resolver/commit knobs)."""
    k = Knobs("ServerKnobs")
    k.define("VERSIONS_PER_SECOND", 1_000_000)
    k.define(
        "MAX_READ_TRANSACTION_LIFE_VERSIONS",
        5_000_000,
        randomize=lambda r: int(
            r.choice([1_000_000, 2_000_000, 5_000_000])
        ),
    )
    k.define(
        "MAX_WRITE_TRANSACTION_LIFE_VERSIONS",
        5_000_000,
        randomize=lambda r: int(
            r.choice([1_000_000, 2_000_000, 5_000_000])
        ),
    )
    k.define("RESOLVER_STATE_MEMORY_LIMIT", 1_000_000)
    k.define(
        "COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 0.001,
        randomize=lambda r: float(r.choice([0.001, 0.005, 0.01])),
    )
    # Adaptive commit batching (the reference's dynamic commitBatcher,
    # fdbserver/CommitProxyServer.actor.cpp:361 + ServerKnobs
    # COMMIT_TRANSACTION_BATCH_*): the interval SHRINKS when batches
    # fill early (load) and relaxes when dispatches go out underfull;
    # batch count/bytes targets follow the measured resolve+log stage
    # latency. All movement is bounded by these knobs.
    k.define(
        "COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 0.020,
        randomize=lambda r: float(r.choice([0.010, 0.020, 0.050])),
    )
    k.define("COMMIT_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA", 0.1)
    # the interval tracks this fraction of the smoothed resolve+log
    # stage latency (the reference's BATCH_INTERVAL_LATENCY_FRACTION):
    # slow stages earn longer windows (bigger batches amortize a fixed
    # per-dispatch cost), fast pipelines shrink back toward MIN
    k.define("COMMIT_TRANSACTION_BATCH_INTERVAL_LATENCY_FRACTION", 0.1)
    k.define("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 32768)
    k.define("COMMIT_TRANSACTION_BATCH_BYTES_MAX", 8 << 20)
    # per-batch resolve+log stage-latency budget the count/bytes targets
    # steer toward (seconds): latency above budget shrinks the targets,
    # latency under half budget with full batches grows them
    k.define("COMMIT_BATCH_STAGE_LATENCY_BUDGET", 0.100)
    # GRV batching follows the same controller (GrvProxyServer's
    # START_TRANSACTION_BATCH_* discipline)
    k.define("START_TRANSACTION_BATCH_INTERVAL_MIN", 0.0005)
    k.define(
        "START_TRANSACTION_BATCH_INTERVAL_MAX", 0.010,
        randomize=lambda r: float(r.choice([0.005, 0.010, 0.020])),
    )
    k.define("START_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA", 0.1)
    k.define("START_TRANSACTION_BATCH_COUNT_MAX", 65536)
    # Bounded GRV front-door queue (the reference's START_TRANSACTION_
    # MAX_QUEUE_SIZE): read-version requests past this depth are SHED
    # with the retryable grv_throttled error instead of queueing
    # unboundedly — overload degrades into delayed admits + client
    # backoff, never into an ever-growing promise list. NOT randomized:
    # ordinary ensemble seeds must not shed by surprise; overload
    # scenarios tighten it explicitly.
    k.define("GRV_PROXY_MAX_QUEUE", 8192)
    # Commit-pipeline depth: how many commit batches may be in flight
    # concurrently through resolve -> tlog-push -> reply, ordered only
    # at the Notified-chain handoffs (the reference bounds pipelining
    # the same way via the resolution/logging version chains).
    k.define("MAX_PIPELINED_COMMIT_BATCHES", 16)
    k.define("RESOLVER_BACKEND", "tpu")  # the resolver_backend knob
    # Below this batch capacity the TPU path cannot win: per-dispatch
    # overhead dominates and the CPU resolves a small batch in well
    # under the device round trip. The default is the MEASURED
    # single-dispatch crossover (scripts/sweep_small.py on v5e,
    # sweep_small_r5*.log; device-resident p50 vs CPU skiplist p50):
    #   n:            512   2048   8192   16384  32768  65536
    #   device txn/s: 4.2K  16.8K  64K    112K   203K   347K
    #   cpu txn/s:    701K  756K   485K   543K   465K   338K
    # — the device first beats the CPU at n=65536 with inputs
    # device-resident. The RESIDENT basis is deliberate: the TPU
    # resolver operates in GROUPED dispatch with double-buffered
    # staging (~0.9-1.1M txn/s at 64K batches — transfer overlapped
    # with compute), and the sweep's transfer-inclusive numbers pay a
    # dev-tunnel RTT a production PCIe host does not. make_conflict_set
    # auto-selects the CPU backend for configs under the threshold — a
    # deliberate, measured TPU-first design decision: the accelerator
    # serves the loaded/batched regime, the CPU serves the latency
    # regime. tests/test_routing_crossover.py pins this decision.
    k.define("RESOLVER_TPU_MIN_BATCH", 65536)
    # Encryption-at-rest (fdbclient/ServerKnobs.cpp ENABLE_ENCRYPTION +
    # fdbserver/EncryptKeyProxy.actor.cpp): storage WAL/checkpoint/LSM
    # payloads are AES-256-CTR sealed under per-domain keys served by
    # the EncryptKeyProxy. Consumed by multiprocess._serve_role; NOT
    # randomized in the sim ensemble — the soak's storage is the
    # in-process sim role, which has no disk to seal (the reference
    # randomizes it because its simulated disks are real files).
    k.define("ENABLE_ENCRYPTION", False)
    # Encryption keys re-derive under a fresh salt after this many
    # seconds (ServerKnobs ENCRYPT_KEY_REFRESH_INTERVAL).
    k.define("ENCRYPT_KEY_REFRESH_INTERVAL", 600.0)
    # Version-vector unicast (default off, like the reference's
    # ENABLE_VERSION_VECTOR_TLOG_UNICAST, fdbclient/ServerKnobs.cpp):
    # resolvers track a per-tlog previous-commit-version vector and
    # replies carry tpcvMap + writtenTags (ResolverInterface.h:140-151).
    k.define("ENABLE_VERSION_VECTOR_TLOG_UNICAST", False)
    # TLog memory budget (in retained mutations) before old unpopped
    # versions spill by reference to the DiskQueue — a lagging storage
    # follower must not grow tlog memory without bound
    # (fdbserver/TLogServer.actor.cpp:2311 + TLOG_SPILL_THRESHOLD)
    k.define(
        "TLOG_SPILL_THRESHOLD", 1_000_000,
        randomize=lambda r: int(r.choice([20, 100, 1_000, 1_000_000])),
    )
    # BUGGIFY: proxies re-send resolve requests (a retry after a lost
    # reply) so the resolver's duplicate-reply window is exercised —
    # Resolver.actor.cpp:513's cached-reply path and the Never() path
    # for requests pruned from the window.
    k.define("BUGGIFY_DUPLICATE_RESOLVE", False)
    # Resolver-generated private mutations + resolver-side txnStateStore
    # (fdbclient/ServerKnobs.cpp:549-550 — randomized under test there too)
    k.define(
        "PROXY_USE_RESOLVER_PRIVATE_MUTATIONS", False,
        randomize=lambda r: bool(r.integers(0, 2)),
    )
    return k


SERVER_KNOBS = make_server_knobs()
