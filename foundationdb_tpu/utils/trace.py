"""Structured trace logging: TraceEvent + the commit-path micro-events.

Behavioral mirror of `flow/Trace.cpp`:

* `TraceEvent(type).detail(k, v)` builds one structured event; events
  carry severity, (virtual) time, role id; sinks render JSON lines (the
  reference's JsonTraceLogFormatter) to memory or a file with rolling.
* `trace_batch` (`g_traceBatch`, flow/Trace.h:576): low-overhead
  commit/GRV-path micro-events with Location strings
  ("Resolver.resolveBatch.Before"...) used for latency debugging — the
  TPU resolver emits the same locations so the reference's
  commit-debugging methodology (contrib/commit_debug.py) transfers.
* `trace_counters` (fdbrpc/Stats.h:93): periodic counter snapshot events.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional

SEV_DEBUG = 5
SEV_INFO = 10
SEV_WARN = 20
SEV_WARN_ALWAYS = 30
SEV_ERROR = 40


class TraceEvent:
    def __init__(self, event_type: str, *, severity: int = SEV_INFO,
                 logger: "TraceLog" = None):
        self.type = event_type
        self.severity = severity
        self.fields: dict[str, Any] = {}
        self._logger = logger or g_trace

    def detail(self, key: str, value) -> "TraceEvent":
        self.fields[key] = value
        return self

    def log(self) -> None:
        self._logger.emit(self)

    # context-manager sugar: `with TraceEvent("X") as e: e.detail(...)`
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.log()
        return False


class TraceLog:
    """In-memory + optional JSONL-file sink with severity filtering."""

    def __init__(self, *, min_severity: int = SEV_INFO,
                 clock: Optional[Callable[[], float]] = None,
                 path: Optional[str] = None, max_events: int = 100_000):
        self.min_severity = min_severity
        self.clock = clock or (lambda: 0.0)
        self.events: list[dict] = []
        self.max_events = max_events
        self._fh = open(path, "a") if path else None

    def emit(self, ev: TraceEvent) -> None:
        if ev.severity < self.min_severity:
            return
        rec = {"Type": ev.type, "Severity": ev.severity,
               "Time": round(self.clock(), 6), **ev.fields}
        self.events.append(rec)
        if len(self.events) > self.max_events:  # rolling, like file rolls
            del self.events[: self.max_events // 2]
        if self._fh:
            self._fh.write(json.dumps(_jsonable(rec)) + "\n")

    def find(self, event_type: str) -> list[dict]:
        return [e for e in self.events if e["Type"] == event_type]

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


def _jsonable(rec):
    return {
        k: (v.decode("latin-1") if isinstance(v, bytes) else v)
        for k, v in rec.items()
    }


class TraceBatch:
    """g_traceBatch: (name, id, location) micro-events on the hot path."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self.clock = clock or (lambda: 0.0)
        self.events: list[tuple[float, str, str, str]] = []
        self.enabled = True

    def add_event(self, name: str, ident: str, location: str) -> None:
        if self.enabled:
            self.events.append((self.clock(), name, ident, location))

    def add_attach(self, name: str, ident: str, to: str) -> None:
        if self.enabled:
            self.events.append((self.clock(), name, ident, f"attach:{to}"))

    def dump(self) -> list[tuple[float, str, str, str]]:
        out, self.events = self.events, []
        return out


def trace_counters(logger: TraceLog, name: str, ident: str, counters) -> None:
    """Periodic counter snapshot (CounterCollection::traceCounters)."""
    ev = TraceEvent(name, logger=logger).detail("ID", ident)
    for k, v in counters.as_dict().items():
        ev.detail(k, v)
    ev.log()


#: process-global default sinks (swappable in tests / roles)
g_trace = TraceLog()
g_trace_batch = TraceBatch()
