"""Structured trace logging: TraceEvent + the commit-path micro-events.

Behavioral mirror of `flow/Trace.cpp`:

* `TraceEvent(type).detail(k, v)` builds one structured event; events
  carry severity, (virtual) time, role id; sinks render JSON lines (the
  reference's JsonTraceLogFormatter) to memory or a file with rolling.
* `trace_batch` (`g_traceBatch`, flow/Trace.h:576): low-overhead
  commit/GRV-path micro-events with Location strings
  ("Resolver.resolveBatch.Before"...) used for latency debugging — the
  TPU resolver emits the same locations so the reference's
  commit-debugging methodology (contrib/commit_debug.py; here
  scripts/commit_debug.py) transfers.
* `trace_counters` (fdbrpc/Stats.h:93): periodic counter snapshot events.

The process-global sinks (`g_trace`, `g_trace_batch`) are swappable per
run via `install()` — a simulation seed installs fresh sinks bound to
the virtual clock so trace output is deterministic and bit-reproducible
per (seed, perturb), then restores the previous ones.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

from foundationdb_tpu.utils.probes import code_probe, declare

declare("metrics.counters_flushed")

SEV_DEBUG = 5
SEV_INFO = 10
SEV_WARN = 20
SEV_WARN_ALWAYS = 30
SEV_ERROR = 40


class TraceEvent:
    def __init__(self, event_type: str, *, severity: int = SEV_INFO,
                 logger: "TraceLog" = None):
        self.type = event_type
        self.severity = severity
        self.fields: dict[str, Any] = {}
        self._logger = logger or g_trace

    def detail(self, key: str, value) -> "TraceEvent":
        self.fields[key] = value
        return self

    def log(self) -> None:
        self._logger.emit(self)

    # context-manager sugar: `with TraceEvent("X") as e: e.detail(...)`
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.log()
        return False


class TraceLog:
    """In-memory + optional JSONL-file sink with severity filtering.

    Both sinks roll at `max_events`: the in-memory list drops its oldest
    half, and the file sink rotates `path` -> `path + ".1"` (one
    generation retained, the reference's rolled-file discipline) so a
    long run's trace is bounded on disk too. Tools that want a complete
    trace (scripts/commit_debug.py) read `path.1` + `path`, or raise
    max_events for the run.
    """

    def __init__(self, *, min_severity: int = SEV_INFO,
                 clock: Optional[Callable[[], float]] = None,
                 path: Optional[str] = None, max_events: int = 100_000):
        self.min_severity = min_severity
        self.clock = clock or (lambda: 0.0)
        self.events: list[dict] = []
        self.max_events = max_events
        self.path = path
        self.rolls = 0
        self._fh = open(path, "a") if path else None
        self._file_events = 0

    def emit(self, ev: TraceEvent) -> None:
        if ev.severity < self.min_severity:
            return
        # an explicit "Time" detail wins over the sink clock: batched
        # micro-events (TraceBatch) carry their own capture time
        rec = {"Type": ev.type, "Severity": ev.severity,
               "Time": round(self.clock(), 6), **ev.fields}
        self.events.append(rec)
        if len(self.events) > self.max_events:  # rolling, like file rolls
            del self.events[: self.max_events // 2]
        if self._fh:
            self._fh.write(json.dumps(_jsonable(rec)) + "\n")
            # flushed per event: file sinks live in role processes that
            # die by SIGTERM (cluster/multiprocess.py), and a buffered
            # tail lost on kill would hole the cross-process timeline
            self._fh.flush()
            self._file_events += 1
            if self._file_events >= self.max_events:
                self._roll_file()

    def _roll_file(self) -> None:
        """Rotate the file sink: current -> .1 (previous .1 dropped)."""
        self.rolls += 1
        self._file_events = 0
        self._fh.close()
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "a")

    def find(self, event_type: str) -> list[dict]:
        return [e for e in self.events if e["Type"] == event_type]

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


def _jsonable(rec):
    return {
        k: (v.decode("latin-1") if isinstance(v, bytes) else v)
        for k, v in rec.items()
    }


class TraceBatch:
    """g_traceBatch: (name, id, location) micro-events on the hot path.

    With a `logger`, every event lands in that TraceLog as a structured
    record (Type=name, ID, Location, Time) — the shape the reference's
    batched events take in the trace file, and what
    scripts/commit_debug.py ingests. The in-process buffer (`dump()`)
    is only kept WITHOUT a logger: the TraceLog is the bounded sink of
    record, and duplicating every micro-event into an unbounded list
    nothing drains would grow without limit on long traced runs.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 *, logger: Optional[TraceLog] = None, enabled: bool = True):
        self.clock = clock or (lambda: 0.0)
        self.events: list[tuple[float, str, str, str]] = []
        self.enabled = enabled
        self.logger = logger

    def _record(self, name: str, ident: str, location: str) -> None:
        t = self.clock()
        if self.logger is not None:
            TraceEvent(name, severity=SEV_DEBUG, logger=self.logger) \
                .detail("ID", ident).detail("Location", location) \
                .detail("Time", round(t, 6)).log()
        else:
            self.events.append((t, name, ident, location))

    def add_event(self, name: str, ident: str, location: str) -> None:
        if self.enabled:
            self._record(name, ident, location)

    def add_attach(self, name: str, ident: str, to: str) -> None:
        if self.enabled:
            self._record(name, ident, f"attach:{to}")

    def dump(self) -> list[tuple[float, str, str, str]]:
        out, self.events = self.events, []
        return out


def trace_counters(logger: TraceLog, name: str, ident: str, counters) -> None:
    """Periodic counter snapshot (CounterCollection::traceCounters)."""
    code_probe(True, "metrics.counters_flushed")
    ev = TraceEvent(name, logger=logger).detail("ID", ident)
    for k, v in counters.as_dict().items():
        ev.detail(k, v)
    ev.log()


#: process-global default sinks (swappable in tests / roles / seeds)
g_trace = TraceLog()
g_trace_batch = TraceBatch(enabled=False)  # enabled per run via install()


def install(log: TraceLog, batch: TraceBatch):
    """Install per-run sinks; returns the previous (log, batch) pair so
    callers can restore them (the spans.set_exporter discipline)."""
    global g_trace, g_trace_batch
    old = (g_trace, g_trace_batch)
    g_trace, g_trace_batch = log, batch
    return old
