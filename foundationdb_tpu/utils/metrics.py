"""Counters and latency samples: the Stats.h / DDSketch analog.

Mirrors the reference's observability surface for the resolver baseline:
`Counter`/`CounterCollection::traceCounters`
(fdbrpc/include/fdbrpc/Stats.h:77-113) and the latency distributions
(`LatencySample`, DDSketch — fdbrpc/include/fdbrpc/DDSketch.h). The
sketch here is a log-bucketed histogram with the same relative-error
contract as DDSketch (gamma = 1 + 2*eps), enough for p50/p95/p99 parity
reporting without the reference's mergeability machinery.
"""

from __future__ import annotations

import math
from typing import Optional

from foundationdb_tpu.utils.probes import code_probe, declare

declare("metrics.latency_band_overflow")


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n


class CounterCollection:
    """Named counter group; `trace()` renders one structured event line."""

    def __init__(self, name: str, counters: list[str] = ()):  # type: ignore[assignment]
        self.name = name
        self._counters: dict[str, Counter] = {}
        for c in counters:
            self._counters[c] = Counter(c)

    def __getitem__(self, name: str) -> Counter:
        if name not in self._counters:
            self._counters[name] = Counter(name)
        return self._counters[name]

    def add(self, name: str, n: int = 1) -> None:
        self[name].add(n)

    def get(self, name: str) -> int:
        return self[name].value

    def as_dict(self) -> dict[str, int]:
        return {k: c.value for k, c in self._counters.items()}


class LatencySample:
    """Log-bucketed quantile sketch (DDSketch-style, relative error eps)."""

    def __init__(self, name: str, eps: float = 0.01):
        self.name = name
        self.eps = eps
        self._gamma = (1 + eps) / (1 - eps)
        self._log_gamma = math.log(self._gamma)
        self._buckets: dict[int, int] = {}
        self._zero = 0
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def sample(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if value <= 0:
            self._zero += 1
            return
        idx = math.ceil(math.log(value) / self._log_gamma)
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = q * (self.count - 1)
        if rank < self._zero:
            return 0.0
        acc = self._zero
        for idx in sorted(self._buckets):
            acc += self._buckets[idx]
            if acc > rank:
                # midpoint of bucket (gamma^(idx-1), gamma^idx]
                return 2.0 * self._gamma**idx / (1 + self._gamma)
        return self.max or 0.0

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def as_dict(self) -> dict[str, float]:
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "max": self.max or 0.0,
        }


#: the reference's default commit/GRV/read latency band thresholds
#: (seconds) — fdbclient/ServerKnobs.cpp *_LATENCY_BANDS; status readers
#: expect stable bucket edges, so these are module constants, not knobs.
COMMIT_LATENCY_BANDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 1.0)
GRV_LATENCY_BANDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.1, 1.0)
READ_LATENCY_BANDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.1, 1.0)


class LatencyBands:
    """Fixed-threshold latency histogram (fdbrpc/Stats.h LatencyBands).

    Each sample lands in the first band whose upper threshold covers it;
    samples above every threshold land in the `inf` overflow bucket —
    the band the reference's status schema renders as the catch-all
    (and the one worth a CODE_PROBE: an overflow hit means the
    operation blew past every budget the bands encode).
    """

    def __init__(self, name: str, bands=COMMIT_LATENCY_BANDS):
        self.name = name
        self.bands = tuple(sorted(bands))
        self.counts = [0] * (len(self.bands) + 1)  # +1: overflow bucket
        self.total = 0

    def add(self, latency: float) -> None:
        self.total += 1
        for i, ub in enumerate(self.bands):
            if latency <= ub:
                self.counts[i] += 1
                return
        code_probe(True, "metrics.latency_band_overflow")
        self.counts[-1] += 1

    def as_dict(self) -> dict[str, int]:
        """Band upper-bound -> count, the status-schema shape
        (`latency_statistics` buckets in Schemas.cpp)."""
        out: dict[str, int] = {"total": self.total}
        for ub, c in zip(self.bands, self.counts):
            out[f"{ub:g}"] = c
        out["inf"] = self.counts[-1]
        return out
