"""Counters and latency samples: the Stats.h / DDSketch analog.

Mirrors the reference's observability surface for the resolver baseline:
`Counter`/`CounterCollection::traceCounters`
(fdbrpc/include/fdbrpc/Stats.h:77-113) and the latency distributions
(`LatencySample`, DDSketch — fdbrpc/include/fdbrpc/DDSketch.h). The
sketch here is a log-bucketed histogram with the same relative-error
contract as DDSketch (gamma = 1 + 2*eps), enough for p50/p95/p99 parity
reporting without the reference's mergeability machinery.
"""

from __future__ import annotations

import math
import time as _time
from typing import Callable, Optional

from foundationdb_tpu.utils.probes import code_probe, declare

declare("metrics.latency_band_overflow")


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n


class CounterCollection:
    """Named counter group; `trace()` renders one structured event line."""

    def __init__(self, name: str, counters: list[str] = ()):  # type: ignore[assignment]
        self.name = name
        self._counters: dict[str, Counter] = {}
        for c in counters:
            self._counters[c] = Counter(c)

    def __getitem__(self, name: str) -> Counter:
        if name not in self._counters:
            self._counters[name] = Counter(name)
        return self._counters[name]

    def add(self, name: str, n: int = 1) -> None:
        self[name].add(n)

    def get(self, name: str) -> int:
        return self[name].value

    def as_dict(self) -> dict[str, int]:
        return {k: c.value for k, c in self._counters.items()}


class Smoother:
    """Exponential time-decay smoother (fdbrpc/Stats.h:77-113 Smoother).

    Tracks a TOTAL whose smoothed estimate decays toward the true total
    with e-folding time `folding_time`: after one folding time, ~63% of
    a step change is reflected; `smooth_rate()` is the decayed estimate
    of d(total)/dt — the reference's Ratekeeper feeds storage/TLog queue
    byte totals through exactly this filter before computing a rate
    limit, so transient spikes don't whipsaw admission.

    The clock is injected: simulation roles pass the scheduler's VIRTUAL
    clock so smoothed values are deterministic per seed (and safe next
    to the trace-digest determinism contract); wire roles pass a wall
    clock (see TimerSmoother). Updates at a non-advancing clock are
    absorbed exactly (the decay factor is 1 at dt=0).
    """

    __slots__ = ("folding_time", "clock", "time", "total", "estimate")

    def __init__(self, folding_time: float,
                 clock: Optional[Callable[[], float]] = None):
        if folding_time <= 0:
            raise ValueError(f"folding_time must be > 0, got {folding_time}")
        self.folding_time = folding_time
        self.clock = clock or (lambda: 0.0)
        self.reset(0.0)

    def reset(self, value: float) -> None:
        self.time = self.clock()
        self.total = value
        self.estimate = value

    def _update(self) -> None:
        t = self.clock()
        elapsed = t - self.time
        if elapsed > 0:
            self.time = t
            self.estimate += (self.total - self.estimate) * (
                1.0 - math.exp(-elapsed / self.folding_time)
            )

    def set_total(self, total: float) -> None:
        self.add_delta(total - self.total)

    def add_delta(self, delta: float) -> None:
        self._update()
        self.total += delta

    def smooth_total(self) -> float:
        self._update()
        return self.estimate

    def smooth_rate(self) -> float:
        """Decayed d(total)/dt — the signal the reference's queue-bytes
        and version-rate smoothers expose to Ratekeeper."""
        self._update()
        return (self.total - self.estimate) / self.folding_time


class TimerSmoother(Smoother):
    """Smoother on the wall clock (the reference's TimerSmoother uses
    timer() where Smoother uses now()): for wire-mode role processes,
    where there is no virtual clock. Never use inside a simulation —
    wall-clock-derived values must stay out of traced output (the
    trace-digest determinism contract)."""

    def __init__(self, folding_time: float):
        super().__init__(folding_time, clock=_time.monotonic)


class Gauge:
    """A named current-value sensor: set() directly, or bind a supplier
    callable so readers always see the live value (the status JSON's
    pull model — the reference's StorageQueueInfo fields are exactly
    this shape, sampled at status time)."""

    __slots__ = ("name", "_value", "_supplier")

    def __init__(self, name: str, supplier: Optional[Callable[[], float]] = None):
        self.name = name
        self._value = 0.0
        self._supplier = supplier

    def set(self, value: float) -> None:
        self._value = value

    def get(self) -> float:
        if self._supplier is not None:
            return self._supplier()
        return self._value


class MetricHistory:
    """Bounded ring buffer of (time, value) samples: sparkline-grade
    time series for fdbtop's per-role history columns. Fixed capacity,
    O(1) append, oldest-first iteration; memory is bounded however long
    the process lives (the TraceLog rolling discipline for gauges)."""

    __slots__ = ("capacity", "_buf", "_next", "_full")

    def __init__(self, capacity: int = 60):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.capacity = capacity
        self._buf: list = [None] * capacity
        self._next = 0
        self._full = False

    def append(self, t: float, value: float) -> None:
        self._buf[self._next] = (t, value)
        self._next = (self._next + 1) % self.capacity
        if self._next == 0:
            self._full = True

    def __len__(self) -> int:
        return self.capacity if self._full else self._next

    def samples(self) -> list[tuple[float, float]]:
        """Oldest-first (time, value) pairs."""
        if not self._full:
            return [s for s in self._buf[: self._next]]
        return [
            s for s in self._buf[self._next:] + self._buf[: self._next]
        ]

    def values(self) -> list[float]:
        return [v for _t, v in self.samples()]

    def last(self) -> Optional[float]:
        n = len(self)
        if n == 0:
            return None
        return self._buf[(self._next - 1) % self.capacity][1]


def sparkline(values: list[float], width: int = 24) -> str:
    """Render a value series as a unicode sparkline (fdbtop's history
    column). Scales to the series' own min/max; empty series -> ''."""
    if not values:
        return ""
    ticks = "▁▂▃▄▅▆▇█"
    vals = values[-width:]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return ticks[0] * len(vals)
    return "".join(
        ticks[min(len(ticks) - 1, int((v - lo) / span * len(ticks)))]
        for v in vals
    )


class LatencySample:
    """Log-bucketed quantile sketch (DDSketch-style, relative error eps)."""

    def __init__(self, name: str, eps: float = 0.01):
        self.name = name
        self.eps = eps
        self._gamma = (1 + eps) / (1 - eps)
        self._log_gamma = math.log(self._gamma)
        self._buckets: dict[int, int] = {}
        self._zero = 0
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def sample(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if value <= 0:
            self._zero += 1
            return
        idx = math.ceil(math.log(value) / self._log_gamma)
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = q * (self.count - 1)
        if rank < self._zero:
            return 0.0
        acc = self._zero
        for idx in sorted(self._buckets):
            acc += self._buckets[idx]
            if acc > rank:
                # midpoint of bucket (gamma^(idx-1), gamma^idx]
                return 2.0 * self._gamma**idx / (1 + self._gamma)
        return self.max or 0.0

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def as_dict(self) -> dict[str, float]:
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "max": self.max or 0.0,
        }


#: the reference's default commit/GRV/read latency band thresholds
#: (seconds) — fdbclient/ServerKnobs.cpp *_LATENCY_BANDS; status readers
#: expect stable bucket edges, so these are module constants, not knobs.
COMMIT_LATENCY_BANDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 1.0)
GRV_LATENCY_BANDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.1, 1.0)
READ_LATENCY_BANDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.1, 1.0)


class LatencyBands:
    """Fixed-threshold latency histogram (fdbrpc/Stats.h LatencyBands).

    Each sample lands in the first band whose upper threshold covers it;
    samples above every threshold land in the `inf` overflow bucket —
    the band the reference's status schema renders as the catch-all
    (and the one worth a CODE_PROBE: an overflow hit means the
    operation blew past every budget the bands encode).
    """

    def __init__(self, name: str, bands=COMMIT_LATENCY_BANDS):
        self.name = name
        self.bands = tuple(sorted(bands))
        self.counts = [0] * (len(self.bands) + 1)  # +1: overflow bucket
        self.total = 0

    def add(self, latency: float) -> None:
        self.total += 1
        for i, ub in enumerate(self.bands):
            if latency <= ub:
                self.counts[i] += 1
                return
        code_probe(True, "metrics.latency_band_overflow")
        self.counts[-1] += 1

    def as_dict(self) -> dict[str, int]:
        """Band upper-bound -> count, the status-schema shape
        (`latency_statistics` buckets in Schemas.cpp)."""
        out: dict[str, int] = {"total": self.total}
        for ub, c in zip(self.bands, self.counts):
            out[f"{ub:g}"] = c
        out["inf"] = self.counts[-1]
        return out
