"""Host-side utilities: packing, metrics, tracing."""
