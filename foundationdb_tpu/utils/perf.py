"""The unified performance ledger: one canonical run-record schema.

Every perf CLI in this repo (bench.py, scripts/bench_pipeline.py,
scripts/saturation.py, scripts/soak.py --trace, scripts/kernel_smoke.py)
emits its headline numbers through `emit()` into ONE append-only JSONL
ledger — `perf/history.jsonl` — while keeping its existing JSON output
as a view. A ledger row is self-describing:

* `schema_version` — bump on any incompatible shape change.
* `source` — which CLI produced it ("bench", "bench_pipeline",
  "saturation", "soak", "kernel_smoke", "multichip").
* `git_sha` / `timestamp` — provenance (imported historical rows carry
  `timestamp: null` and `imported_from: <artifact>` so re-import is
  byte-stable).
* `fingerprint` — the host/device identity a comparator needs to avoid
  comparing a CPU-host structural run against a v5e hardware run:
  backend, device kind/count, jax/jaxlib versions, python, machine.
  (BENCH_r01..r06 recorded only `backend`, so CPU-host and v5e rows
  were indistinguishable — the r10 satellite this field set fixes.)
* `workload` — the shapes that make two runs comparable (txns, batches,
  mode, spec, seeds, ...).
* `knobs` — the knob fingerprint (kernel kind, delta capacity, dedup,
  fuse, ...): a knob change is a different experiment, not noise.
* `experiment` — OPTIONAL: the autotune search this row is a TRIAL of
  (scripts/autotune.py stamps the search id). Experiment rows are the
  searcher's resumability cache — fingerprint-keyed, so a re-run skips
  already-measured configurations — and are EXCLUDED from baseline
  windows in both directions: a normal candidate never compares against
  trials, and a trial row can never be accepted as a committed baseline
  (`perfcheck --accept` refuses it). The search winner is re-emitted
  WITHOUT the field through `perfcheck --check --accept`.
* `metrics` — a FLAT name -> {value, unit, direction, tier} map.
  direction is "higher" | "lower" (which way is better); tier is
  "structural" (deterministic on any host: merge-row counts, compile
  counts, batch/shed/abort counts, bytes on the wire — compared
  exactly) or "hardware" (wall-clock rates/latencies — compared inside
  a median-of-N + MAD noise band, armed only when the fingerprints
  match).

The comparator (`compare()`, CLI scripts/perfcheck.py) selects the
baseline window from the ledger by fingerprint key, applies
median + MAD bands per metric, and reports regressions — the
`perf.regression_gate_tripped` probe fires on any. scripts/check.sh
gates the structural tier on every PR; the hardware tier arms when the
fingerprint shows a real accelerator.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Optional

from foundationdb_tpu.utils.probes import declare, code_probe

declare("perf.regression_gate_tripped")

SCHEMA_VERSION = 1

#: metrics directions: which way is BETTER
DIRECTIONS = ("higher", "lower")
#: structural = deterministic on any host (exact compare);
#: hardware = wall-clock (noise-banded, fingerprint-gated)
TIERS = ("structural", "hardware")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: fingerprint fields that make hardware-tier rows comparable — a
#: different device kind/count or jaxlib is a different experiment
HARDWARE_FP_KEYS = ("backend", "device_kind", "device_count",
                    "jaxlib_version")


def perf_dir() -> str:
    return os.environ.get(
        "FDBTPU_PERF_DIR", os.path.join(_REPO_ROOT, "perf")
    )


def history_path() -> str:
    """The canonical ledger file. `FDBTPU_PERF_LEDGER` redirects every
    emitter at once (CI smoke lanes point it at a tempfile so green
    runs don't dirty the committed history)."""
    return os.environ.get(
        "FDBTPU_PERF_LEDGER", os.path.join(perf_dir(), "history.jsonl")
    )


# ---------------------------------------------------------------------------
# Fingerprints.


def device_fingerprint() -> dict:
    """The full host/device identity for a ledger row.

    bench.py's old `backend` field alone cannot distinguish a CPU-host
    structural run from a v5e hardware run; the comparator needs device
    kind/count and the jaxlib version (an XLA upgrade resets hardware
    baselines). Never raises: a host without a working JAX still gets a
    row (backend "none") so non-device CLIs can emit."""
    import platform

    fp = {
        "backend": "none",
        "device_kind": None,
        "device_count": 0,
        "jax_version": None,
        "jaxlib_version": None,
        "python_version": platform.python_version(),
        "machine": platform.machine(),
    }
    try:
        import jax
        import jaxlib

        fp["jax_version"] = jax.__version__
        fp["jaxlib_version"] = jaxlib.__version__
        devices = jax.devices()
        fp["backend"] = jax.default_backend()
        fp["device_count"] = len(devices)
        fp["device_kind"] = devices[0].device_kind if devices else None
    except Exception:
        pass
    return fp


def _git_sha() -> Optional[str]:
    try:
        import subprocess

        return subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=_REPO_ROOT,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Records.


def metric(value, unit: str, direction: str = "lower",
           tier: str = "hardware") -> dict:
    """One metrics-map entry; validated again at append time."""
    if direction not in DIRECTIONS:
        raise ValueError(f"direction must be one of {DIRECTIONS}")
    if tier not in TIERS:
        raise ValueError(f"tier must be one of {TIERS}")
    return {"value": value, "unit": unit, "direction": direction,
            "tier": tier}


_NOW = object()  # sentinel: stamp at build time


def make_record(source: str, metrics: dict, *, workload: dict = None,
                knobs: dict = None, fingerprint: dict = None,
                timestamp=_NOW, git_sha=None,
                imported_from: str = None, extra: dict = None,
                experiment: str = None) -> dict:
    """Assemble one schema-valid ledger row. Imported historical rows
    carry `timestamp: null` / `git_sha: null` (unless given) so the
    migration is byte-stable — re-running --import reproduces
    identical bytes. `experiment` marks the row an autotune TRIAL
    (absent on every non-trial row, keeping pre-r15 bytes stable)."""
    import time as _time

    rec = {
        "schema_version": SCHEMA_VERSION,
        "source": source,
        "git_sha": git_sha if (git_sha or imported_from) else _git_sha(),
        "timestamp": (
            None if imported_from
            else (round(_time.time(), 3) if timestamp is _NOW
                  else timestamp)
        ),
        "fingerprint": (
            fingerprint if fingerprint is not None else device_fingerprint()
        ),
        "workload": workload or {},
        "knobs": knobs or {},
        "metrics": metrics,
    }
    if imported_from:
        rec["imported_from"] = imported_from
    if experiment:
        rec["experiment"] = experiment
    if extra:
        rec["extra"] = extra
    validate_record(rec)
    return rec


def validate_record(rec: dict) -> None:
    """Raise ValueError (naming every problem) unless `rec` is a
    schema-valid ledger row."""
    problems = []
    if rec.get("schema_version") != SCHEMA_VERSION:
        problems.append(
            f"schema_version must be {SCHEMA_VERSION}, "
            f"got {rec.get('schema_version')!r}"
        )
    if not rec.get("source") or not isinstance(rec.get("source"), str):
        problems.append("source must be a non-empty string")
    fp = rec.get("fingerprint")
    if not isinstance(fp, dict):
        problems.append("fingerprint must be a dict")
    else:
        for key in ("backend", "device_kind", "device_count",
                    "jax_version", "jaxlib_version"):
            if key not in fp:
                problems.append(f"fingerprint missing {key!r}")
    for key in ("workload", "knobs"):
        if not isinstance(rec.get(key), dict):
            problems.append(f"{key} must be a dict")
    if "experiment" in rec and not (
        isinstance(rec["experiment"], str) and rec["experiment"]
    ):
        problems.append("experiment must be a non-empty string when present")
    metrics = rec.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        problems.append("metrics must be a non-empty dict")
    else:
        for name, m in metrics.items():
            if not isinstance(m, dict):
                problems.append(f"metric {name!r} must be a dict")
                continue
            if not isinstance(m.get("value"), (int, float)) or isinstance(
                m.get("value"), bool
            ):
                problems.append(f"metric {name!r} value must be a number")
            if m.get("direction") not in DIRECTIONS:
                problems.append(
                    f"metric {name!r} direction must be one of {DIRECTIONS}"
                )
            if m.get("tier") not in TIERS:
                problems.append(
                    f"metric {name!r} tier must be one of {TIERS}"
                )
            if "unit" not in m:
                problems.append(f"metric {name!r} missing unit")
    if problems:
        raise ValueError(
            "invalid perf record: " + "; ".join(problems)
        )


def append(rec: dict, path: str = None) -> str:
    """Validate + append one row to the ledger; returns the path."""
    validate_record(rec)
    path = path or history_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    return path


def emit(source: str, metrics: dict, *, workload: dict = None,
         knobs: dict = None, ledger: str = None, extra: dict = None,
         experiment: str = None) -> dict:
    """The one call every perf CLI makes: build a row for THIS host and
    append it to the ledger (or `ledger`/$FDBTPU_PERF_LEDGER)."""
    rec = make_record(source, metrics, workload=workload, knobs=knobs,
                      extra=extra, experiment=experiment)
    append(rec, path=ledger)
    return rec


def load_history(path: str = None) -> list[dict]:
    """All ledger rows, oldest first. Strict: a malformed line is a
    corrupted ledger, not noise to skip."""
    path = path or history_path()
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: malformed ledger line "
                                 f"({e})") from e
    return rows


# ---------------------------------------------------------------------------
# Baseline selection + the noise-aware comparator.


def fingerprint_key(rec: dict, tier: str) -> tuple:
    """The comparability key for baseline selection.

    Structural metrics are deterministic on ANY host (merge-row counts,
    batch counts, shed/abort counts), so the key is (source, workload,
    knobs) — rows from different machines still gate each other. The
    hardware tier adds the device identity: wall-clock rates only
    compare within (backend, device kind/count, jaxlib)."""
    key = (
        rec.get("source"),
        json.dumps(rec.get("workload", {}), sort_keys=True),
        json.dumps(rec.get("knobs", {}), sort_keys=True),
    )
    if tier == "hardware":
        fp = rec.get("fingerprint", {})
        key += tuple(fp.get(k) for k in HARDWARE_FP_KEYS)
    return key


def baseline_window(history: list[dict], candidate: dict, *, tier: str,
                    window: int = 8) -> list[dict]:
    """The most recent `window` ledger rows comparable to `candidate`
    at `tier` (matching fingerprint key, same schema). Rows with a
    mismatched fingerprint are ignored, never 'close enough'.
    EXPERIMENT rows (autotune trials) are never baselines: a trial runs
    a deliberately non-default knob point, so comparing a committed
    configuration against it would gate the tree on a configuration
    nobody shipped."""
    want = fingerprint_key(candidate, tier)
    matched = [
        r for r in history
        if r.get("schema_version") == candidate.get("schema_version")
        and not r.get("experiment")
        and fingerprint_key(r, tier) == want
    ]
    return matched[-window:]


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _mad(xs: list[float], med: float) -> float:
    return _median([abs(x - med) for x in xs])


def compare(candidate: dict, history: list[dict], *, tier: str,
            window: int = 8, k_mad: float = 4.0,
            rel_floor: float = None) -> dict:
    """Noise-aware regression check of one candidate row against the
    ledger.

    Per metric in `candidate` at `tier`: take the matching-fingerprint
    baseline window, compute median + MAD, and flag a regression when
    the candidate lands OUTSIDE median +/- max(k_mad * 1.4826 * MAD,
    rel_floor * |median|) in the WORSE direction (improvements never
    fail — they widen the next window instead). Defaults: structural
    rel_floor 0.0 (deterministic values compare exactly — a doubled
    merge-row count is a regression, not noise), hardware rel_floor
    0.05 (shared-host timers swing; the MAD term grows the band when
    the recorded history is noisier than 5%).

    Returns {"tier", "baseline_rows", "metrics": {name: {...}},
    "regressions": [names]}. Fires perf.regression_gate_tripped when
    any metric regresses. A candidate with NO comparable baseline rows
    reports every metric "new" and passes — the seeding path.
    """
    if rel_floor is None:
        rel_floor = 0.0 if tier == "structural" else 0.05
    base = baseline_window(history, candidate, tier=tier, window=window)
    out: dict[str, Any] = {
        "tier": tier,
        "baseline_rows": len(base),
        "metrics": {},
        "regressions": [],
    }
    for name, m in sorted(candidate.get("metrics", {}).items()):
        if m.get("tier") != tier:
            continue
        samples = [
            float(r["metrics"][name]["value"]) for r in base
            if name in r.get("metrics", {})
        ]
        entry: dict[str, Any] = {
            "value": float(m["value"]),
            "unit": m.get("unit"),
            "direction": m.get("direction"),
            "n_baseline": len(samples),
        }
        if not samples:
            entry["status"] = "new"
            out["metrics"][name] = entry
            continue
        med = _median(samples)
        band = max(
            k_mad * 1.4826 * _mad(samples, med), rel_floor * abs(med)
        )
        entry.update(baseline_median=med, band=band)
        value = float(m["value"])
        worse = (
            value < med - band if m.get("direction") == "higher"
            else value > med + band
        )
        better = (
            value > med + band if m.get("direction") == "higher"
            else value < med - band
        )
        entry["status"] = (
            "regression" if worse else "improved" if better else "ok"
        )
        if worse:
            out["regressions"].append(name)
        out["metrics"][name] = entry
    code_probe(out["regressions"], "perf.regression_gate_tripped")
    return out


# ---------------------------------------------------------------------------
# JAX device / compile profiling hooks.


def profile_trace(profile_dir: Optional[str]):
    """Context manager: capture a `jax.profiler` device/host trace into
    `profile_dir` (xplane protos viewable in TensorBoard/XProf); a
    no-op when the dir is falsy or the profiler is unavailable, so
    callers gate on nothing."""
    import contextlib

    if not profile_dir:
        return contextlib.nullcontext()
    try:
        import jax

        os.makedirs(profile_dir, exist_ok=True)
        return jax.profiler.trace(profile_dir)
    except Exception:
        return contextlib.nullcontext()


def device_memory_stats(device=None) -> dict:
    """Live-buffer / peak device memory for one device, normalized to
    {"bytes_in_use", "peak_bytes_in_use", ...}. Empty on backends that
    don't report (XLA:CPU returns None) — samplers treat empty as
    'nothing to record', never an error."""
    try:
        import jax

        dev = device or jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size", "num_allocs"):
        if key in stats:
            out[key] = int(stats[key])
    return out


def cost_analysis_of(jitted, *args, **kwargs) -> dict:
    """HLO cost-model extraction for one compiled program: FLOPs and
    bytes accessed (plus transcendentals when reported), normalized
    key names. With the persistent compile cache on, lower+compile of
    an already-warm signature is a cache hit, so recording this per
    bench run is cheap. Empty dict on any failure — the roofline
    comparison is an observability extra, never a gate."""
    try:
        analysis = jitted.lower(*args, **kwargs).compile().cost_analysis()
    except Exception:
        return {}
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return {}
    out = {}
    for key, norm in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals"),
                      ("optimal_seconds", "optimal_seconds")):
        v = analysis.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[norm] = float(v)
    return out


# ---------------------------------------------------------------------------
# Converters: one shared row shape per CLI, used by BOTH the live
# emitters and the historical-artifact importer (scripts/perfcheck.py
# --import) so imported baselines and fresh rows land on the same
# fingerprint keys.


def bench_row_to_metrics(row: dict) -> dict:
    """bench.py's printed JSON row -> the ledger metrics map."""
    m = {
        "txn_s": metric(row.get("value", 0.0), "txn/s", "higher"),
        "vs_baseline": metric(row.get("vs_baseline", 0.0), "ratio",
                              "higher"),
    }
    for src, name, unit, direction in (
        ("device_resident_txn_s", "device_resident_txn_s", "txn/s",
         "higher"),
        ("baseline_txns_per_sec", "cpu_baseline_txn_s", "txn/s", "higher"),
        ("p50_ms", "latency_p50_ms", "ms", "lower"),
        ("p99_ms", "latency_p99_ms", "ms", "lower"),
        ("p50_incl_transfer_ms", "latency_incl_transfer_p50_ms", "ms",
         "lower"),
    ):
        if src in row:
            m[name] = metric(row[src], unit, direction)
    abl = row.get("ablation") or {}
    for src, name in (
        ("merge_rows_classic_per_group", "merge_rows_classic_per_group"),
        ("merge_rows_tiered_per_batch_cap", "merge_rows_tiered_cap"),
        ("merge_rows_tiered_per_batch_live", "merge_rows_tiered_live"),
        ("delta_live_boundaries", "delta_live_boundaries"),
        ("main_live_boundaries", "main_live_boundaries"),
    ):
        if src in abl:
            m[name] = metric(abl[src], "rows", "lower", tier="structural")
    for src, name in (("pack_ms_per_group", "pack_ms_per_group"),
                      ("transfer_ms_per_group", "transfer_ms_per_group"),
                      ("kernel_ms_per_group", "kernel_ms_per_group"),
                      ("fence_ms_per_group", "fence_ms_per_group")):
        if src in abl:
            m[name] = metric(abl[src], "ms", "lower")
    # ISSUE 14 structural accounting (absent on pre-r14 rows, keeping
    # the historical --import byte-stable): total decisions over the
    # seeded stream plus the range-path counters — deterministic on any
    # host, gated exactly by perfcheck (the YCSB-E acceptance row)
    st = row.get("structural") or {}
    for src, name, direction in (
        ("committed", "decisions_committed", "higher"),
        ("conflicted", "decisions_conflicted", "lower"),
        ("too_old", "decisions_too_old", "lower"),
        ("spills", "spills", "lower"),
        ("sweep_groups", "sweep_groups", "higher"),
        ("compactions", "compactions", "lower"),
    ):
        if src in st:
            m[name] = metric(st[src], "count", direction, tier="structural")
    if "sweep_rows_per_group" in st:
        m["sweep_rows_per_group"] = metric(
            st["sweep_rows_per_group"], "rows", "lower", tier="structural"
        )
    cc = row.get("compile_cache") or {}
    if cc:
        # both counters depend on persistent-cache warmth (JAX fires
        # backend_compile_duration only on an ACTUAL XLA compile; a
        # cache hit skips it) -> hardware tier, informational: a
        # recompile explosion is visible in the ledger without a cold
        # first run on a fresh clone false-failing the exact gate
        m["compile_count"] = metric(
            cc.get("backend_compiles", 0), "count", "lower"
        )
        m["compile_cache_misses"] = metric(
            cc.get("cache_misses", cc.get("misses", 0)), "count", "lower"
        )
    # HLO cost-model numbers depend on the XLA backend and compiler
    # version (fusion changes bytes accessed), so they live in the
    # hardware tier: compared only between matching device/jaxlib
    # fingerprints, never exact-gated across hosts
    hlo = row.get("hlo_cost") or {}
    if "flops" in hlo:
        m["kernel_flops"] = metric(hlo["flops"], "flops", "lower")
    if "bytes_accessed" in hlo:
        m["kernel_bytes_accessed"] = metric(
            hlo["bytes_accessed"], "bytes", "lower"
        )
    return m


def bench_row_to_record(row: dict, *, imported_from: str = None,
                        fingerprint: dict = None) -> dict:
    """bench.py row -> full ledger record (live or imported)."""
    if fingerprint is None:
        fp = {k: None for k in ("device_kind", "jax_version",
                                "jaxlib_version", "python_version",
                                "machine")}
        fp["backend"] = row.get("backend")
        fp["device_count"] = 1 if row.get("backend") else 0
        fingerprint = fp
    workload = {
        "metric": row.get("metric"),
        "batches": row.get("batches"),
        "staging": row.get("staging", "device"),
    }
    knobs = {
        "kernel": row.get("kernel"),
        "fused_dispatch": row.get("fused_dispatch"),
        "delta_capacity": row.get("delta_capacity"),
        "dedup_reads": row.get("dedup_reads"),
        "compact_interval": row.get("compact_interval"),
    }
    # r14 knobs join the fingerprint only when present, so every
    # pre-r14 row's baseline key is unchanged (import byte-stability)
    for k in ("range_sweep", "delta_spill"):
        if row.get(k):
            knobs[k] = row[k]
    return make_record(
        "bench", bench_row_to_metrics(row), workload=workload, knobs=knobs,
        fingerprint=fingerprint, imported_from=imported_from,
    )


def pipeline_row_to_records(row: dict, *, imported_from: str = None,
                            fingerprint: dict = None) -> list[dict]:
    """bench_pipeline.py row (one per run, N backends) -> one ledger
    record per backend."""
    recs = []
    # committed/conflicted/ops counts are STRUCTURAL only in cluster
    # mode (the deterministic virtual-clock simulation); a wire run's
    # retry counts ride real asyncio timing and belong in the
    # noise-banded hardware tier
    count_tier = "structural" if row.get("mode") == "cluster" else "hardware"
    for backend, res in (row.get("backends") or {}).items():
        if fingerprint is None:
            fp = {k: None for k in ("device_kind", "jax_version",
                                    "jaxlib_version", "python_version",
                                    "machine")}
            fp["backend"] = backend
            fp["device_count"] = 0
            this_fp = fp
        else:
            this_fp = dict(fingerprint)
        metrics = {
            "txn_s": metric(res.get("txn_s", 0.0), "txn/s", "higher"),
            "commit_p50_ms": metric(res.get("commit_p50_ms", 0.0), "ms",
                                    "lower"),
            "commit_p99_ms": metric(res.get("commit_p99_ms", 0.0), "ms",
                                    "lower"),
            "committed": metric(res.get("committed", 0), "txns", "higher",
                                tier=count_tier),
            "conflicted": metric(res.get("conflicted", 0), "txns", "lower",
                                 tier=count_tier),
        }
        if "ops" in res:
            metrics["ops"] = metric(res["ops"], "ops", "higher",
                                    tier=count_tier)
        # columnar wire path (r12): the resolver role's copy/alloc
        # accounting is STRUCTURAL — path-determined ratios (copies
        # per batch, decode allocs per txn), deterministic regardless
        # of batching/timing — so the "two copies" claim is gated
        # exactly by perfcheck, not asserted in prose. Only present on
        # runs that report it (keeps the historical --import
        # byte-stable: PIPELINE_r0x rows predate the metric).
        if "resolve_copies_per_batch" in res:
            metrics["resolve_copies_per_batch"] = metric(
                res["resolve_copies_per_batch"], "copies", "lower",
                tier="structural",
            )
        if "resolve_decode_allocs_per_txn" in res:
            metrics["resolve_decode_allocs_per_txn"] = metric(
                res["resolve_decode_allocs_per_txn"], "allocs", "lower",
                tier="structural",
            )
        knobs = {
            "batch": row.get("batch"),
            "kernel_txns": row.get("kernel_txns"),
            "kernel": row.get("kernel"),
        }
        if row.get("resolve_path"):
            # frame A/B knob: keys columnar and object rows apart in
            # the baseline fingerprint (absent on pre-r12 rows and
            # cluster-mode rows, so their keys are unchanged)
            knobs["resolve_path"] = row["resolve_path"]
        if row.get("knob_overrides"):
            # autotune trials drive server knobs through the env hook;
            # the applied overrides key each trial apart (absent on
            # every non-trial row — import byte-stability)
            knobs.update(row["knob_overrides"])
        recs.append(make_record(
            "bench_pipeline", metrics,
            workload={
                "spec": row.get("spec"),
                "mode": row.get("mode"),
                "inflight": row.get("inflight"),
                "ops_per_client": row.get("ops_per_client"),
                "records": row.get("records"),
                "resolver_backend": backend,
            },
            knobs=knobs,
            fingerprint=this_fp, imported_from=imported_from,
        ))
    return recs


def saturation_report_to_record(rep: dict, *, imported_from: str = None,
                                fingerprint: dict = None) -> dict:
    """testing/saturation report (one direction) -> ledger record.
    Everything is structural: the ramp runs on the deterministic
    virtual clock, so p99s and shed counts are exact per seed."""
    if fingerprint is None:
        fingerprint = {
            "backend": "cpu", "device_kind": None, "device_count": 0,
            "jax_version": None, "jaxlib_version": None,
            "python_version": None, "machine": None,
        }
    steps = rep.get("steps") or []
    worst_p99 = max((s.get("commit_p99_s", 0.0) for s in steps),
                    default=0.0)
    metrics = {
        "peak_goodput_tps": metric(rep.get("peak_goodput_tps", 0.0), "tps",
                                   "higher", tier="structural"),
        "worst_commit_p99_s": metric(worst_p99, "s", "lower",
                                     tier="structural"),
        "shed_total": metric(sum(s.get("shed", 0) for s in steps), "txns",
                             "lower", tier="structural"),
        "too_old_total": metric(
            sum(s.get("too_old", 0) for s in steps), "txns", "lower",
            tier="structural",
        ),
        "committed_total": metric(
            sum(s.get("committed", 0) for s in steps), "txns", "higher",
            tier="structural",
        ),
        "slo_passed": metric(
            int(bool((rep.get("slo") or {}).get("passed"))), "bool",
            # the OFF direction is SUPPOSED to violate; direction is
            # meaningful only per admission leg, encoded in workload
            "higher" if rep.get("admission") else "lower",
            tier="structural",
        ),
    }
    return make_record(
        "saturation", metrics,
        workload={
            "spec": rep.get("spec"),
            "seed": rep.get("seed"),
            "admission": bool(rep.get("admission")),
            "ramp": rep.get("ramp"),
            "step_seconds": rep.get("step_seconds"),
        },
        knobs=rep.get("config") or {},
        fingerprint=fingerprint, imported_from=imported_from,
    )


def hotspot_report_to_record(rep: dict, *, imported_from: str = None,
                             fingerprint: dict = None) -> dict:
    """testing/hotspot report (one leg) -> ledger record: the sampling
    overhead envelope. Only the SIM legs belong in the committed
    history — the byte sample is a pure function of (seed, key, size)
    and the tag counters run on the virtual clock, so every count here
    is structural (exact-compared by perfcheck). Wire legs use
    wall-entropy sampling seeds; ledger them only for local notes."""
    if fingerprint is None:
        fingerprint = {
            "backend": "cpu", "device_kind": None, "device_count": 0,
            "jax_version": None, "jaxlib_version": None,
            "python_version": None, "machine": None,
        }
    samp = rep.get("sampling") or {}
    skewed = rep.get("direction") == "zipf"
    metrics = {
        "sample_keys": metric(samp.get("sample_keys", 0), "keys", "lower",
                              tier="structural"),
        "sampled_bytes": metric(samp.get("sampled_bytes", 0), "bytes",
                                "lower", tier="structural"),
        "committed": metric(rep.get("committed", 0), "txns", "higher",
                            tier="structural"),
        # the verdict itself: the zipf leg is SUPPOSED to attribute,
        # the uniform leg is supposed to stay quiet — direction is
        # meaningful only per leg, encoded in workload
        "attributed": metric(
            int(bool((rep.get("attribution") or {}).get("attributed"))),
            "bool", "higher" if skewed else "lower", tier="structural",
        ),
    }
    for name, unit in (
        ("byte_sample_writes", "writes"),
        ("tag_counter_tags", "tags"),
        ("tag_notes", "notes"),
        ("tag_bytes_noted", "bytes"),
        ("resolver_key_sample_keys", "keys"),
    ):
        if name in samp:
            metrics[name] = metric(samp[name], unit, "lower",
                                   tier="structural")
    cfg = rep.get("config") or {}
    return make_record(
        "hotspot", metrics,
        workload={
            "spec": rep.get("spec", "hotspot"),
            "seed": rep.get("seed"),
            "path": rep.get("path"),
            "direction": rep.get("direction"),
            "txns": cfg.get("txns"),
            "value_bytes": cfg.get("value_bytes"),
        },
        knobs=cfg,
        fingerprint=fingerprint, imported_from=imported_from,
    )


def multichip_artifact_to_record(obj: dict, *, imported_from: str = None,
                                 fingerprint: dict = None) -> dict:
    """MULTICHIP_r0*.json (the 8-device lane's pass/fail artifact) ->
    ledger record."""
    if fingerprint is None:
        fingerprint = {
            "backend": "cpu", "device_kind": None,
            "device_count": obj.get("n_devices", 0),
            "jax_version": None, "jaxlib_version": None,
            "python_version": None, "machine": None,
        }
    metrics = {
        "ok": metric(int(bool(obj.get("ok"))), "bool", "higher",
                     tier="structural"),
        "rc": metric(obj.get("rc", 0), "code", "lower", tier="structural"),
        "skipped": metric(int(bool(obj.get("skipped"))), "bool", "lower",
                          tier="structural"),
    }
    return make_record(
        "multichip", metrics,
        workload={"n_devices": obj.get("n_devices", 0)},
        fingerprint=fingerprint, imported_from=imported_from,
    )
