"""OTEL-style spans: trace contexts threaded through every request.

The reference threads a `Span`/`SpanContext` through each RPC
(fdbclient/Tracing.actor.cpp; `ResolveTransactionBatchRequest.spanContext`
ResolverInterface.h:129) and exports finished spans to a collector. Same
model here, sized to this framework:

* `SpanContext(trace_id, span_id)` — ids are deterministic when a seeded
  rng is supplied (simulation runs must stay reproducible).
* `Span(location, parent=ctx)` — records start/end (from an injectable
  clock, so virtual time works) plus key-value attributes; `finish()`
  hands it to the active exporter.
* `SpanExporter` — in-memory collector with an optional TraceLog sink
  (the UDP-exporter stand-in); tests and tools read `.finished`.

Wire shape: a span context travels as the (trace_id, span_id) pair on
request dataclasses — `ResolveTransactionBatchRequest.span` carries it to
resolvers exactly where the reference's spanContext field sits.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

_lock = threading.Lock()


@dataclasses.dataclass(frozen=True)
class SpanContext:
    trace_id: int
    span_id: int

    def as_tuple(self) -> tuple[int, int]:
        return (self.trace_id, self.span_id)


def make_context(trace_id: Optional[int] = None) -> SpanContext:
    """New context. Ids come from the ACTIVE exporter's counter, so a
    fresh exporter (one per simulation run / test) yields reproducible
    ids — rerun-identical determinism holds for span output too."""
    with _lock:
        _exporter._next_id += 1
        sid = _exporter._next_id
    return SpanContext(trace_id=trace_id if trace_id is not None else sid,
                       span_id=sid)


class SpanExporter:
    """Collects finished spans (the UDP exporter / collector role)."""

    def __init__(self, trace_log=None, *, max_finished: int = 10_000):
        self.finished: list[dict] = []
        self.trace_log = trace_log
        self.max_finished = max_finished
        self._next_id = 0  # span-id counter (see make_context)

    def export(self, span: "Span") -> None:
        rec = {
            "location": span.location,
            "trace_id": span.context.trace_id,
            "span_id": span.context.span_id,
            "parent_id": span.parent.span_id if span.parent else 0,
            "begin": span.begin,
            "end": span.end,
            "attributes": dict(span.attributes),
        }
        self.finished.append(rec)
        if len(self.finished) > self.max_finished:
            del self.finished[: len(self.finished) // 2]
        if self.trace_log is not None:
            from foundationdb_tpu.utils.trace import SEV_DEBUG, TraceEvent

            # detail keys are CamelCase like every reference TraceEvent
            # (the trace.detail-case flowcheck rule); the in-memory
            # `finished` records keep their snake_case shape for tools
            TraceEvent("Span", severity=SEV_DEBUG, logger=self.trace_log) \
                .detail("Location", rec["location"]) \
                .detail("TraceID", rec["trace_id"]) \
                .detail("SpanID", rec["span_id"]) \
                .detail("ParentID", rec["parent_id"]) \
                .detail("Begin", rec["begin"]) \
                .detail("End", rec["end"]).log()

    def traces(self, trace_id: int) -> list[dict]:
        return [s for s in self.finished if s["trace_id"] == trace_id]


#: process-wide exporter; swap with set_exporter() in tests/tools
_exporter = SpanExporter()


def set_exporter(e: SpanExporter) -> SpanExporter:
    """Install `e`; returns the PREVIOUS exporter so callers can
    restore it."""
    global _exporter
    old = _exporter
    _exporter = e
    return old


def get_exporter() -> SpanExporter:
    return _exporter


class Span:
    """One timed operation; finish() exports it.

    Usable as a context manager. `clock` is injectable so simulated time
    traces correctly (Span("x", clock=sched.now)).
    """

    def __init__(self, location: str, *, parent: Optional[SpanContext] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.location = location
        self.parent = parent
        self.context = make_context(
            trace_id=parent.trace_id if parent else None
        )
        self._clock = clock or (lambda: 0.0)
        self.begin = self._clock()
        self.end: Optional[float] = None
        self.attributes: dict = {}
        self._finished = False
        # Bound at CREATION, not finish: a span owned by an abandoned
        # coroutine may only finish when the GC finalizes the generator
        # — inside some LATER run with a different active exporter.
        # Exporting there would pollute that run's (deterministic,
        # digested) trace with this run's leftovers.
        self._exporter = _exporter

    def attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def finish(self) -> None:
        if not self._finished:
            self._finished = True
            self.end = self._clock()
            self._exporter.export(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()
