"""Commit-path trace reconstruction: the contrib/commit_debug.py role.

The reference debugs its commit path by scattering `g_traceBatch`
micro-events (name, id, Location) along the pipeline and reconstructing
per-transaction timelines offline with contrib/commit_debug.py. This
module is that reconstructor as a library (scripts/commit_debug.py is
the CLI; the soak span-chain gate imports the checks), plus the single
source of truth for the Location vocabulary every role emits — the
emitters, the reconstructor and the tests all read the constants here,
so a renamed location cannot silently break the chain gate.

Event shapes ingested (TraceLog records, in memory or JSONL):

* micro-events: ``{"Type": "CommitDebug"|"TransactionDebug",
  "ID": ..., "Location": ..., "Time": ...}`` — `TraceBatch` with a
  logger renders exactly this.
* attaches: the same with ``Location == "attach:<other id>"``
  (`TraceBatch.add_attach`) — a transaction's debug id attaching to its
  commit batch's debug id, the reference's *AttachID discipline.
* ``CommitDebugVersion``: ``{"ID": <batch id>, "Version": v,
  "Messages": n}`` — the proxy's batch-id -> commit-version join record
  (storage applies are keyed by version, not debug id).
* ``Span``: the span exporter's TraceLog sink records.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional

from foundationdb_tpu.utils.probes import code_probe, declare

declare("trace.span_chain_gate_tripped")

# -- the Location vocabulary (reference names; commit_debug.py joins on
# -- these strings, so they are constants, not ad-hoc literals) ----------

GRV_BEFORE = "NativeAPI.getConsistentReadVersion.Before"
GRV_AFTER = "NativeAPI.getConsistentReadVersion.After"
GRV_REPLY = "GrvProxyServer.transactionStarter.ReplyToStartedTransactions"
COMMIT_BEFORE = "NativeAPI.commit.Before"
COMMIT_AFTER = "NativeAPI.commit.After"
BATCH_BEFORE = "CommitProxy.commitBatch.Before"
BATCH_GETTING_VERSION = "CommitProxy.commitBatch.GettingCommitVersion"
BATCH_GOT_VERSION = "CommitProxy.commitBatch.GotCommitVersion"
BATCH_AFTER_RESOLUTION = "CommitProxy.commitBatch.AfterResolution"
BATCH_AFTER_LOG_PUSH = "CommitProxy.commitBatch.AfterLogPush"
#: columnar wire path (r12): the proxy finished packing the batch's
#: conflict metadata into the columnar frame (flat arrays + key blob)
PROXY_COLUMNAR_PACK = "CommitProxy.commitBatch.ColumnarPack"
RESOLVER_BEFORE = "Resolver.resolveBatch.Before"
RESOLVER_AFTER_QUEUE = "Resolver.resolveBatch.AfterQueueSizeCheck"
RESOLVER_AFTER_ORDERER = "Resolver.resolveBatch.AfterOrderer"
#: columnar wire path (r12): the resolver turned the frame into the
#: conflict backend's input (kernel tensors / reconstructed objects);
#: with AfterOrderer as the opening mark, the waterfall's
#: columnar_decode stage brackets exactly the decode
RESOLVER_COLUMNAR_DECODE = "Resolver.resolveBatch.ColumnarDecode"
RESOLVER_AFTER = "Resolver.resolveBatch.After"
TLOG_BEFORE_WAIT = "TLog.tLogCommit.BeforeWaitForVersion"
TLOG_AFTER_COMMIT = "TLog.tLogCommit.AfterTLogCommit"
STORAGE_APPLIED = "StorageServer.update.Applied"

#: ident prefix for version-keyed events (storage applies happen below
#: the debug-id horizon; the CommitDebugVersion record joins them)
VERSION_ID_PREFIX = "@"

#: the stages a committed transaction's batch must have traversed —
#: missing any of these = a broken chain (the soak gate's contract)
REQUIRED_BATCH_LOCATIONS = (
    BATCH_BEFORE,
    BATCH_GOT_VERSION,
    BATCH_AFTER_RESOLUTION,
    BATCH_AFTER_LOG_PUSH,
    RESOLVER_BEFORE,
    RESOLVER_AFTER,
    TLOG_AFTER_COMMIT,
)

MICRO_EVENT_TYPES = ("CommitDebug", "TransactionDebug", "CommitAttachID")


def version_id(version: int) -> str:
    return f"{VERSION_ID_PREFIX}{version}"


@dataclasses.dataclass
class Timeline:
    """One committed transaction's reconstructed commit-path timeline."""

    debug_id: str
    batch_id: Optional[str]
    version: Optional[int]
    #: (time, location) across every stage, time-ascending
    events: list[tuple[float, str]]

    def locations(self) -> set[str]:
        return {loc for _t, loc in self.events}

    def first(self, location: str) -> Optional[float]:
        for t, loc in self.events:
            if loc == location:
                return t
        return None

    def stage_durations(self) -> dict[str, float]:
        """The waterfall row: per-stage seconds, NaN-free (absent stages
        are simply omitted)."""
        marks = {}
        for t, loc in self.events:
            marks.setdefault(loc, t)
        out: dict[str, float] = {}

        def stage(name, a, b):
            if a in marks and b in marks and marks[b] >= marks[a]:
                out[name] = marks[b] - marks[a]

        stage("grv", GRV_BEFORE, GRV_AFTER)
        stage("batching", COMMIT_BEFORE, BATCH_BEFORE)
        stage("get_version", BATCH_BEFORE, BATCH_GOT_VERSION)
        # columnar wire path (r12): proxy-side pack and resolver-side
        # decode attributed explicitly inside the resolution window —
        # absent on object-path runs, so an --aggregate A/B shows
        # exactly where the microseconds went
        stage("columnar_pack", BATCH_GOT_VERSION, PROXY_COLUMNAR_PACK)
        stage("columnar_decode", RESOLVER_AFTER_ORDERER,
              RESOLVER_COLUMNAR_DECODE)
        stage("resolution", BATCH_GOT_VERSION, BATCH_AFTER_RESOLUTION)
        stage("logging", BATCH_AFTER_RESOLUTION, BATCH_AFTER_LOG_PUSH)
        stage("reply", BATCH_AFTER_LOG_PUSH, COMMIT_AFTER)
        stage("total", COMMIT_BEFORE, COMMIT_AFTER)
        return out


class TraceIndex:
    """Parsed trace records, indexed for reconstruction."""

    def __init__(self, records: Iterable[dict]):
        #: id -> [(time, location)], micro-events only, insertion order
        self.micro: dict[str, list[tuple[float, str]]] = {}
        #: txn debug id -> batch debug id (attach records)
        self.attach: dict[str, str] = {}
        #: batch debug id -> (version, message count)
        self.batch_version: dict[str, tuple[int, int]] = {}
        #: exported span records (the Span sink's shape)
        self.spans: list[dict] = []
        for rec in records:
            rtype = rec.get("Type")
            if rtype == "CommitDebugVersion":
                self.batch_version[rec["ID"]] = (
                    int(rec["Version"]), int(rec.get("Messages", 0))
                )
            elif rtype == "Span":
                self.spans.append(rec)
            elif rtype in MICRO_EVENT_TYPES and "Location" in rec:
                ident, loc = rec["ID"], rec["Location"]
                if loc.startswith("attach:"):
                    self.attach[ident] = loc[len("attach:"):]
                else:
                    self.micro.setdefault(ident, []).append(
                        (float(rec["Time"]), loc)
                    )

    # -- reconstruction --------------------------------------------------

    def committed_ids(self) -> list[str]:
        """Debug ids whose client observed a successful commit."""
        return sorted(
            ident for ident, evs in self.micro.items()
            if any(loc == COMMIT_AFTER for _t, loc in evs)
        )

    def timeline(self, debug_id: str) -> Timeline:
        events = list(self.micro.get(debug_id, []))
        batch_id = self.attach.get(debug_id)
        version = msg_count = None
        if batch_id is not None:
            events += self.micro.get(batch_id, [])
            bv = self.batch_version.get(batch_id)
            if bv is not None:
                version, msg_count = bv
                events += self.micro.get(version_id(version), [])
        events.sort()
        return Timeline(
            debug_id=debug_id, batch_id=batch_id, version=version,
            events=events,
        )

    def timelines(self) -> list[Timeline]:
        return [self.timeline(i) for i in self.committed_ids()]


# -- the chain-integrity gate -------------------------------------------


def check_chains(index: TraceIndex) -> list[str]:
    """Violations of the commit-chain contract: every committed
    transaction must show the full GRV -> commit -> resolve -> tlog ->
    storage pipeline. Returns human-readable violation strings (empty =
    clean); fires the `trace.span_chain_gate_tripped` probe on any."""
    violations: list[str] = []
    for tl in index.timelines():
        locs = tl.locations()
        if COMMIT_BEFORE not in locs:
            violations.append(
                f"{tl.debug_id}: {COMMIT_AFTER} without {COMMIT_BEFORE}"
            )
        # a preset read version (sideband-style pinning) legitimately
        # skips GRV; an ISSUED GRV must have completed
        if GRV_BEFORE in locs and GRV_AFTER not in locs:
            violations.append(f"{tl.debug_id}: GRV issued but never answered")
        if tl.batch_id is None:
            violations.append(
                f"{tl.debug_id}: committed but never attached to a batch"
            )
            continue
        missing = [l for l in REQUIRED_BATCH_LOCATIONS if l not in locs]
        if missing:
            violations.append(
                f"{tl.debug_id} (batch {tl.batch_id}): missing pipeline "
                f"stage(s) {missing}"
            )
        if tl.version is None:
            violations.append(
                f"{tl.debug_id} (batch {tl.batch_id}): no "
                "CommitDebugVersion record"
            )
        else:
            _v, msgs = index.batch_version[tl.batch_id]
            if msgs > 0 and STORAGE_APPLIED not in locs:
                violations.append(
                    f"{tl.debug_id} (batch {tl.batch_id}, version "
                    f"{tl.version}): {msgs} storage message tag(s) but no "
                    f"{STORAGE_APPLIED} event"
                )
    violations += check_spans(index.spans)
    code_probe(bool(violations), "trace.span_chain_gate_tripped")
    return violations


def check_spans(spans: list[dict]) -> list[str]:
    """Span sanity over exported records (either the exporter's
    `finished` dicts or their TraceLog "Span" sink shape): no orphan
    parents, no end-before-start in (virtual) time."""
    def field(s, snake, camel):
        return s[snake] if snake in s else s[camel]

    ids = {field(s, "span_id", "SpanID") for s in spans}
    out: list[str] = []
    for s in spans:
        loc = field(s, "location", "Location")
        sid = field(s, "span_id", "SpanID")
        parent = field(s, "parent_id", "ParentID")
        begin, end = field(s, "begin", "Begin"), field(s, "end", "End")
        if parent and parent not in ids:
            out.append(f"span {sid} ({loc}): orphan parent {parent}")
        if end is None or end < begin:
            out.append(
                f"span {sid} ({loc}): end {end} before begin {begin}"
            )
    return out


# -- the waterfall -------------------------------------------------------


def waterfall(timelines: list[Timeline]) -> dict[str, dict[str, float]]:
    """Aggregate stage durations across timelines: stage ->
    {count, mean, p50, p90, p99, max} (seconds)."""
    stages: dict[str, list[float]] = {}
    for tl in timelines:
        for name, dt in tl.stage_durations().items():
            stages.setdefault(name, []).append(dt)
    out: dict[str, dict[str, float]] = {}
    for name, xs in stages.items():
        xs.sort()
        out[name] = {
            "count": len(xs),
            "mean": sum(xs) / len(xs),
            "p50": xs[len(xs) // 2],
            "p90": xs[min(len(xs) - 1, int(len(xs) * 0.90))],
            "p99": xs[min(len(xs) - 1, int(len(xs) * 0.99))],
            "max": xs[-1],
        }
    return out


def text_histogram(xs: list[float], width: int = 40) -> list[str]:
    """Power-of-two latency histogram (the reference trace event
    histogram shape): one line per occupied bucket, `#` bar scaled to
    the modal bucket. Input seconds; buckets labeled in ms."""
    if not xs:
        return []
    import math

    buckets: dict[int, int] = {}
    for x in xs:
        ms = x * 1e3
        b = -60 if ms <= 0 else math.floor(math.log2(ms))
        buckets[b] = buckets.get(b, 0) + 1
    peak = max(buckets.values())
    lines = []
    for b in sorted(buckets):
        lo = 0.0 if b == -60 else 2.0 ** b
        hi = 2.0 ** (b + 1)
        n = buckets[b]
        bar = "#" * max(1, round(n / peak * width))
        lines.append(f"[{lo:10.3f}, {hi:10.3f}) ms  {n:6d}  {bar}")
    return lines


def render_timeline(tl: Timeline) -> str:
    lines = [
        f"txn {tl.debug_id}  batch={tl.batch_id}  version={tl.version}"
    ]
    t0 = tl.events[0][0] if tl.events else 0.0
    for t, loc in tl.events:
        lines.append(f"  {(t - t0) * 1e3:9.3f}ms  {loc}")
    return "\n".join(lines)


def load_jsonl(paths: list[str]) -> list[dict]:
    """Read TraceLog JSONL files (pass rolled `.1` files first for a
    complete, time-ordered trace)."""
    import json

    records: list[dict] = []
    for path in paths:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
    return records
