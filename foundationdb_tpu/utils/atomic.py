"""Atomic mutation semantics (MutationRef::Type).

Behavioral mirror of the reference's atomic operations
(fdbclient/include/fdbclient/CommitTransaction.h:32-71 MutationRef types;
apply semantics in fdbserver/storageserver.actor.cpp applyMutation /
fdbclient/AtomicOps.h... doAdd/doAnd/...): little-endian arithmetic over
byte strings, zero-extension to the operand length, saturating/wrapping
exactly as the reference does.
"""

from __future__ import annotations

from typing import Optional

ATOMIC_OPS = (
    "add", "bit_and", "bit_or", "bit_xor", "max", "min",
    "byte_min", "byte_max", "append_if_fits", "compare_and_clear",
)

VALUE_SIZE_LIMIT = 100_000  # CLIENT_KNOBS->VALUE_SIZE_LIMIT


def _le_int(b: bytes) -> int:
    return int.from_bytes(b, "little")


def _pad(b: bytes, n: int) -> bytes:
    return b[:n] + b"\x00" * max(0, n - len(b))


def apply_atomic(op: str, old: Optional[bytes], param: bytes) -> Optional[bytes]:
    """new_value = op(old_value, param); None means 'key absent'."""
    if op == "add":
        # doLittleEndianAdd: absent -> param; wraps modulo 2^(8*len(param))
        if old is None:
            return param
        n = len(param)
        if n == 0:
            return b""
        total = (_le_int(_pad(old, n)) + _le_int(param)) % (1 << (8 * n))
        return total.to_bytes(n, "little")
    if op == "bit_and":
        # doAndV2: absent behaves as zeros
        if old is None:
            return b"\x00" * len(param)
        return bytes(a & b for a, b in zip(_pad(old, len(param)), param))
    if op == "bit_or":
        if old is None:
            return param
        return bytes(a | b for a, b in zip(_pad(old, len(param)), param))
    if op == "bit_xor":
        if old is None:
            return param
        return bytes(a ^ b for a, b in zip(_pad(old, len(param)), param))
    if op == "max":
        # doMax: little-endian unsigned compare at param length
        if old is None or not old:
            return param
        n = len(param)
        return param if _le_int(param) > _le_int(_pad(old, n)) else _pad(old, n)
    if op == "min":
        # doMinV2: absent -> param (sets)
        if old is None:
            return param
        n = len(param)
        return param if _le_int(param) < _le_int(_pad(old, n)) else _pad(old, n)
    if op == "byte_min":
        if old is None:
            return param
        return min(old, param)
    if op == "byte_max":
        if old is None:
            return param
        return max(old, param)
    if op == "append_if_fits":
        base = old or b""
        return base + param if len(base) + len(param) <= VALUE_SIZE_LIMIT else base
    if op == "compare_and_clear":
        # clears the key iff the value equals param
        return None if old == param else old
    raise ValueError(f"unknown atomic op {op!r}")
