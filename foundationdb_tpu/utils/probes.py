"""CODE_PROBE: rare-path coverage assertions collected across ensembles.

The reference marks rare-but-important code paths with
`CODE_PROBE(cond, "msg")` (flow/include/flow/CodeProbe.h) and CI asserts
that every probe fires somewhere across a Joshua ensemble — "this branch
is reachable and our randomization actually reaches it". Same contract
here:

* `declare(name)` registers a probe statically (module import time), so
  a probe whose code never even runs still shows up as a MISS.
* `code_probe(cond, name)` marks a hit when cond is truthy (and
  auto-registers undeclared names defensively).
* `snapshot()` / `reset()` let the ensemble runner (scripts/soak.py)
  aggregate hits across seeds; `tests/test_probes.py` pins the required
  set — the coveragetool role (flow/coveragetool) collapsed to a module.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_hits: dict[str, int] = {}
_declared: set[str] = set()


def declare(*names: str) -> None:
    with _lock:
        _declared.update(names)
        for n in names:
            _hits.setdefault(n, 0)


def code_probe(cond, name: str) -> bool:
    """Record a hit when cond is truthy; returns bool(cond) for inlining
    into existing conditionals."""
    ok = bool(cond)
    if ok:
        with _lock:
            _declared.add(name)
            _hits[name] = _hits.get(name, 0) + 1
    return ok


def snapshot() -> dict[str, int]:
    with _lock:
        return dict(_hits)


def missed() -> list[str]:
    with _lock:
        return sorted(n for n in _declared if not _hits.get(n))


def reset() -> None:
    with _lock:
        for n in list(_hits):
            _hits[n] = 0


def merge(other: dict[str, int]) -> None:
    """Fold a child run's snapshot into this process's counts."""
    with _lock:
        for n, c in other.items():
            _declared.add(n)
            _hits[n] = _hits.get(n, 0) + c
