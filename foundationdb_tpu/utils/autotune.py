"""Ledger-driven knob autotuner: resumable search over the bench knob
space, every trial a fingerprint-keyed EXPERIMENT row in the perf
ledger.

The closed loop the ROADMAP's "self-driving performance" item asks for:

* the SEARCH SPACE is an ordered {knob: (values...)} grid
  (`SearchSpace`) — BENCH_FUSE, the adaptive-batch targets,
  `dedup_reads` vs `range_sweep`, `compact_interval`, `delta_capacity`,
  `n_shards` — walked in a deterministic order so a resumed search
  replays the same trial sequence;
* each TRIAL runs one of the existing harnesses (bench.py /
  scripts/bench_pipeline.py, driven as subprocesses through their env
  knobs + `--perf-ledger`) and lands the emitted row in the search
  ledger with `experiment: <search id>` stamped — utils/perf.py
  excludes experiment rows from every baseline window, so trials can
  NEVER pollute the perfcheck gate;
* the ledger IS the resumability cache: before running a trial the
  searcher scans the ledger for a row with the same (experiment,
  trial_key) and reuses its objective — killing a sweep mid-run and
  re-running completes only the missing trials, across hardware
  sessions (the fingerprint travels in the row, so a v5e trial is
  never confused with a CPU-host trial: `cache_scope="device"`
  restricts hits to matching device fingerprints);
* the STOPPING RULE is roofline distance: with the row's recorded
  `hlo_cost` (bytes accessed / FLOPs per dispatch) and the device's
  peak numbers, `roofline_txn_s` bounds the achievable rate; the
  search stops early once the best trial achieves `roofline_frac` of
  it (default 0.5 — past that, knob search is chasing the compiler).
  Hosts without a known peak (CPU fingerprints) fall back to
  exhaustion / no-improvement stopping, honestly reported;
* the WINNER is promoted by re-emitting its row WITHOUT the
  experiment field (`promote_record`) and handing it to
  `scripts/perfcheck.py --check --accept` — the committed-baseline
  flow, unchanged.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Optional, Sequence

from foundationdb_tpu.utils import perf
from foundationdb_tpu.utils.probes import code_probe, declare

declare("autotune.cache_hit", "autotune.roofline_stop")

#: peak memory bandwidth (bytes/s) by device kind — the roofline's
#: denominator (the resolver kernels are memory-bound scans, so the
#: bytes-accessed bound is the binding one; FLOPs peaks would only
#: loosen it). Unlisted kinds (CPU hosts included: XLA:CPU reports no
#: stable peak) disable the roofline stopping rule.
DEVICE_PEAK_BYTES_S = {
    "TPU v4": 1.2e12,
    "TPU v5 lite": 8.19e11,
    "TPU v5e": 8.19e11,
    "TPU v5p": 2.765e12,
    "TPU v6 lite": 1.64e12,
}


def roofline_txn_s(hlo_cost: dict, fingerprint: dict,
                   txns_per_dispatch: int) -> Optional[float]:
    """The bytes-bound roofline rate for one compiled resolver dispatch:
    txns_per_dispatch / (bytes_accessed / peak_bytes_s). None when the
    cost model or the device peak is unavailable — callers treat None
    as 'no roofline', never as zero."""
    if not hlo_cost or txns_per_dispatch <= 0:
        return None
    bytes_accessed = hlo_cost.get("bytes_accessed")
    peak = DEVICE_PEAK_BYTES_S.get((fingerprint or {}).get("device_kind"))
    if not bytes_accessed or not peak:
        return None
    seconds = float(bytes_accessed) / float(peak)
    if seconds <= 0:
        return None
    return txns_per_dispatch / seconds


class SearchSpace:
    """An ordered knob grid. Deterministic enumeration order (insertion
    order of `knobs`, values left to right, last knob fastest) so a
    resumed search replays the identical trial sequence and the
    fingerprint cache lines up."""

    def __init__(self, knobs: dict[str, Sequence]):
        if not knobs or not all(len(v) > 0 for v in knobs.values()):
            raise ValueError("every knob needs at least one value")
        self.knobs = {k: tuple(v) for k, v in knobs.items()}

    def __len__(self) -> int:
        n = 1
        for v in self.knobs.values():
            n *= len(v)
        return n

    def points(self) -> list[dict]:
        out: list[dict] = [{}]
        for name, values in self.knobs.items():
            out = [{**p, name: v} for p in out for v in values]
        return out


def trial_key(knobs: dict) -> str:
    """The canonical identity of one grid point — what the ledger cache
    matches on (sorted-key JSON, so dict order can't split the cache)."""
    return json.dumps(knobs, sort_keys=True)


@dataclasses.dataclass
class Trial:
    knobs: dict
    objective: Optional[float]  # direction-normalized: HIGHER is better
    record: Optional[dict]      # the ledger row (None: harness failed)
    cached: bool
    error: Optional[str] = None


def _cache_fp_key(rec: dict) -> tuple:
    fp = rec.get("fingerprint") or {}
    return tuple(fp.get(k) for k in perf.HARDWARE_FP_KEYS)


def find_cached(history: list[dict], *, experiment: str, key: str,
                cache_scope: str = "any",
                fingerprint: dict = None) -> Optional[dict]:
    """The resumability lookup: the most recent ledger row carrying
    this search's experiment id and this trial's key. `cache_scope=
    "device"` additionally requires the row's device fingerprint to
    match `fingerprint` (hardware objectives must not resume from a
    different machine's trials; structural objectives may)."""
    want_fp = None
    if cache_scope == "device":
        want_fp = tuple(
            (fingerprint or {}).get(k) for k in perf.HARDWARE_FP_KEYS
        )
    for rec in reversed(history):
        if rec.get("experiment") != experiment:
            continue
        if ((rec.get("extra") or {}).get("trial_key")) != key:
            continue
        if want_fp is not None and _cache_fp_key(rec) != want_fp:
            continue
        return rec
    return None


def objective_of(rec: dict, metric: str) -> Optional[float]:
    """Direction-normalized objective from one ledger row: the metric's
    value, negated when its declared direction is "lower" — the search
    maximizes unconditionally."""
    m = (rec.get("metrics") or {}).get(metric)
    if m is None:
        return None
    v = float(m["value"])
    return v if m.get("direction") == "higher" else -v


def promote_record(rec: dict) -> dict:
    """The winner, stripped of its experiment marker (and trial-key
    extra) so `perfcheck --check --accept` can admit it as a committed
    baseline row. Everything else — fingerprint, workload, knobs,
    metrics — is the trial's own measurement."""
    out = {k: v for k, v in rec.items() if k != "experiment"}
    extra = {k: v for k, v in (out.get("extra") or {}).items()
             if k != "trial_key"}
    if extra:
        out["extra"] = extra
    else:
        out.pop("extra", None)
    perf.validate_record(out)
    return out


@dataclasses.dataclass
class SearchReport:
    experiment: str
    trials: list[Trial]
    best: Optional[Trial]
    stopped: str                  # "roofline" | "exhausted" | "no_improve"
    cache_hits: int
    ran: int
    roofline: Optional[float] = None
    roofline_frac_achieved: Optional[float] = None

    def as_dict(self) -> dict:
        return {
            "experiment": self.experiment,
            "n_trials": len(self.trials),
            "cache_hits": self.cache_hits,
            "ran": self.ran,
            "stopped": self.stopped,
            "best_knobs": self.best.knobs if self.best else None,
            "best_objective": self.best.objective if self.best else None,
            "roofline": self.roofline,
            "roofline_frac_achieved": self.roofline_frac_achieved,
        }


def run_search(
    experiment: str,
    space: SearchSpace,
    run_trial: Callable[[dict], dict],
    *,
    objective_metric: str,
    ledger: str,
    cache_scope: str = "any",
    roofline_frac: float = 0.5,
    roofline_txns_per_dispatch: int = 0,
    no_improve_limit: int = 0,
    log: Callable[[str], None] = None,
) -> SearchReport:
    """Walk the grid; each point either resumes from the ledger cache
    or runs `run_trial(knobs)` (returns a schema row WITHOUT the
    experiment stamp — this function stamps experiment + trial_key and
    appends it to `ledger`).

    Stopping, in precedence order: (1) roofline — when the device peak
    and the best row's `hlo_cost` extra are both known and the best
    achieved rate reaches `roofline_frac` of `roofline_txn_s`;
    (2) no_improve_limit consecutive non-improving trials (0 = off);
    (3) grid exhaustion. A failed trial records error and continues —
    one bad knob point must not kill a resumable sweep."""
    log = log or (lambda *_: None)
    trials: list[Trial] = []
    best: Optional[Trial] = None
    cache_hits = ran = since_improve = 0
    stopped = "exhausted"
    roofline = frac = None
    fingerprint = perf.device_fingerprint()
    history = perf.load_history(ledger)
    for knobs in space.points():
        key = trial_key(knobs)
        rec = find_cached(history, experiment=experiment, key=key,
                          cache_scope=cache_scope, fingerprint=fingerprint)
        cached = rec is not None
        err = None
        if cached:
            cache_hits += 1
            code_probe(True, "autotune.cache_hit")
            log(f"[cache] {key}")
        else:
            try:
                rec = run_trial(dict(knobs))
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                rec, err = None, f"{type(e).__name__}: {e}"
                log(f"[fail]  {key}: {err}")
            if rec is not None:
                rec = dict(rec)
                rec["experiment"] = experiment
                rec.setdefault("extra", {})
                rec["extra"] = {**rec["extra"], "trial_key": key}
                perf.append(rec, path=ledger)
                history.append(rec)
                ran += 1
                log(f"[trial] {key}")
        obj = objective_of(rec, objective_metric) if rec else None
        t = Trial(knobs=knobs, objective=obj, record=rec, cached=cached,
                  error=err)
        trials.append(t)
        if obj is not None and (best is None or obj > best.objective):
            best, since_improve = t, 0
        else:
            since_improve += 1
        # roofline stop: achieved rate (the objective metric must be a
        # higher-is-better rate for this to be meaningful; callers pass
        # roofline_txns_per_dispatch=0 to disable) vs the bytes-bound
        # ceiling from the winner's recorded HLO cost
        if (best is not None and roofline_txns_per_dispatch > 0
                and best.record is not None):
            hlo = dict(
                (best.record.get("extra") or {}).get("hlo_cost") or {}
            )
            if "bytes_accessed" not in hlo:
                # bench rows carry the cost model as metrics
                # (kernel_bytes_accessed, hardware tier)
                m = (best.record.get("metrics") or {}).get(
                    "kernel_bytes_accessed"
                )
                if m is not None:
                    hlo["bytes_accessed"] = float(m["value"])
            roofline = roofline_txn_s(
                hlo, best.record.get("fingerprint"),
                roofline_txns_per_dispatch,
            )
            if roofline:
                frac = best.objective / roofline
                if frac >= roofline_frac:
                    stopped = "roofline"
                    code_probe(True, "autotune.roofline_stop")
                    break
        if no_improve_limit and since_improve >= no_improve_limit:
            stopped = "no_improve"
            break
    return SearchReport(
        experiment=experiment, trials=trials, best=best, stopped=stopped,
        cache_hits=cache_hits, ran=ran, roofline=roofline,
        roofline_frac_achieved=frac,
    )
