"""Persistent XLA compilation cache (VERDICT r1 task 10).

The driver re-runs bench.py in a fresh process every round; without a
persistent cache each run re-pays the full trace+compile of the resolver
kernel (137s at 64K-txn shapes in BENCH_r01.json). JAX's persistent
cache keys on (HLO, compile options, backend version), so a warm cache
drops that to de/serialization time.
"""

from __future__ import annotations

import os
import threading

from foundationdb_tpu.utils.probes import code_probe, declare

declare("perf.compile_cache_miss")

_BASE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_compile_cache")


def _machine_tag() -> str:
    """Short hash of the host's CPU feature set.

    XLA:CPU cache entries embed AOT machine code; loading an entry
    compiled on a host with different ISA features risks SIGILL (the
    loader only warns). The container this repo lives in migrates
    between hosts across rounds, so the cache dir is keyed per-machine.
    """
    import hashlib
    import platform

    # ISA feature lines only ("flags" on x86, "Features" on arm) — the
    # rest of cpuinfo has per-boot noise (MHz, bogomips) that would
    # invalidate the cache on every restart of the same host.
    feature_lines = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feature_lines.add(line.strip())
    except OSError:
        pass
    seed = "|".join(sorted(feature_lines)) or platform.processor()
    return hashlib.md5(
        (platform.machine() + ":" + seed).encode()
    ).hexdigest()[:8]


_DEFAULT = _BASE + "." + _machine_tag()


def enable(path: str | None = None) -> str:
    """Turn on the persistent compilation cache; returns the cache dir.

    Safe to call multiple times and before/after backend init (the cache
    is consulted at compile time, not backend-init time). Also arms the
    compile-observability listeners (`instrument()`), so every enabled
    process carries hit/miss counters and compile seconds in `stats()`.
    """
    import jax

    path = path or os.environ.get("FDBTPU_COMPILE_CACHE", _DEFAULT)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: the kernel's many specializations are each well
    # over the default thresholds anyway, and tiny entries are harmless.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    instrument()
    return path


# ---------------------------------------------------------------------------
# Compile observability (ISSUE 10): JAX emits monitoring events for
# persistent-cache hits/misses and backend-compile durations; this
# module aggregates them into one process-global stats block that
# KernelStageMetrics.qos() / cluster_status() / the perf ledger read.
# Process-global on purpose — the XLA compiler and its cache are too.
# These counters are wall-clock/host-dependent and deliberately stay
# OUT of every CounterCollection the deterministic trace flush ships.

_stats_lock = threading.Lock()
_stats = {
    "cache_hits": 0,
    "cache_misses": 0,
    "backend_compiles": 0,
    "compile_seconds_total": 0.0,
    "last_compile_seconds": 0.0,
}
#: explicit per-signature compile seconds (warm-compile paths that know
#: what they compiled record here; the monitoring listener only knows
#: durations, not signatures)
_signatures: dict[str, float] = {}
_instrumented = False

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_event(event: str, *a, **kw) -> None:
    if event == _HIT_EVENT:
        with _stats_lock:
            _stats["cache_hits"] += 1
    elif event == _MISS_EVENT:
        with _stats_lock:
            _stats["cache_misses"] += 1
        code_probe(True, "perf.compile_cache_miss")


def _on_duration(event: str, duration: float, *a, **kw) -> None:
    if event.endswith("backend_compile_duration"):
        with _stats_lock:
            _stats["backend_compiles"] += 1
            _stats["compile_seconds_total"] += float(duration)
            _stats["last_compile_seconds"] = float(duration)


def instrument() -> bool:
    """Register the jax.monitoring listeners (idempotent). Returns
    whether the listeners are armed — an older/newer JAX without the
    monitoring API degrades to zeros, never an error."""
    global _instrumented
    if _instrumented:
        return True
    try:
        from jax import monitoring

        # resolve BOTH registrars before registering either: failing
        # between the two would leave _instrumented False and a later
        # enable() would register _on_event twice (double counts)
        reg = monitoring.register_event_listener
        reg_duration = monitoring.register_event_duration_secs_listener
    except Exception:
        return False
    _instrumented = True  # before the calls: never re-register
    reg(_on_event)
    reg_duration(_on_duration)
    return True


def record_compile(signature: str, seconds: float) -> None:
    """Per-signature compile seconds, recorded by the code paths that
    know WHAT they compiled (ResolverRole warm compile, bench warm
    loops). Keeps the most recent duration per signature."""
    with _stats_lock:
        _signatures[signature] = float(seconds)


def stats() -> dict:
    """One snapshot: cache hit/miss counters, backend-compile count and
    seconds, and the per-signature compile-seconds map."""
    with _stats_lock:
        out = dict(_stats)
        out["per_signature_compile_seconds"] = dict(_signatures)
    return out


def reset_stats() -> None:
    """Test hook: zero the process-global counters."""
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0.0 if isinstance(_stats[k], float) else 0
        _signatures.clear()
