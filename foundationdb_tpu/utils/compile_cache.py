"""Persistent XLA compilation cache (VERDICT r1 task 10).

The driver re-runs bench.py in a fresh process every round; without a
persistent cache each run re-pays the full trace+compile of the resolver
kernel (137s at 64K-txn shapes in BENCH_r01.json). JAX's persistent
cache keys on (HLO, compile options, backend version), so a warm cache
drops that to de/serialization time.
"""

from __future__ import annotations

import os

_BASE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_compile_cache")


def _machine_tag() -> str:
    """Short hash of the host's CPU feature set.

    XLA:CPU cache entries embed AOT machine code; loading an entry
    compiled on a host with different ISA features risks SIGILL (the
    loader only warns). The container this repo lives in migrates
    between hosts across rounds, so the cache dir is keyed per-machine.
    """
    import hashlib
    import platform

    # ISA feature lines only ("flags" on x86, "Features" on arm) — the
    # rest of cpuinfo has per-boot noise (MHz, bogomips) that would
    # invalidate the cache on every restart of the same host.
    feature_lines = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feature_lines.add(line.strip())
    except OSError:
        pass
    seed = "|".join(sorted(feature_lines)) or platform.processor()
    return hashlib.md5(
        (platform.machine() + ":" + seed).encode()
    ).hexdigest()[:8]


_DEFAULT = _BASE + "." + _machine_tag()


def enable(path: str | None = None) -> str:
    """Turn on the persistent compilation cache; returns the cache dir.

    Safe to call multiple times and before/after backend init (the cache
    is consulted at compile time, not backend-init time).
    """
    import jax

    path = path or os.environ.get("FDBTPU_COMPILE_CACHE", _DEFAULT)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: the kernel's many specializations are each well
    # over the default thresholds anyway, and tiny entries are harmless.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
