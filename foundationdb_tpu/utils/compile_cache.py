"""Persistent XLA compilation cache (VERDICT r1 task 10).

The driver re-runs bench.py in a fresh process every round; without a
persistent cache each run re-pays the full trace+compile of the resolver
kernel (137s at 64K-txn shapes in BENCH_r01.json). JAX's persistent
cache keys on (HLO, compile options, backend version), so a warm cache
drops that to de/serialization time.
"""

from __future__ import annotations

import os
import threading

from foundationdb_tpu.utils.probes import code_probe, declare

declare("perf.compile_cache_miss")

_BASE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_compile_cache")


def _host_feature_lines() -> str:
    """The host identity XLA:CPU AOT entries are sensitive to: ISA
    feature lines PLUS the CPU model name. The model name matters —
    XLA derives microarchitecture tuning pseudo-features from it
    (`prefer-no-gather`/`prefer-no-scatter`), so two hosts with
    byte-identical cpuinfo FLAGS can still produce incompatible AOT
    entries (the MULTICHIP_r05 cpu_aot_loader mismatch spam). MHz /
    bogomips lines stay out: per-boot noise would invalidate the cache
    on every restart of the same host."""
    import platform

    lines = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features", "model name")):
                    lines.add(line.strip())
    except OSError:
        pass
    return "|".join(sorted(lines)) or platform.processor()


def _machine_tag() -> str:
    """Short hash of the host's CPU identity.

    XLA:CPU cache entries embed AOT machine code; loading an entry
    compiled on a host with different ISA features risks SIGILL (the
    loader only warns). The container this repo lives in migrates
    between hosts across rounds, so the cache dir is keyed per-machine.
    """
    import hashlib
    import platform

    return hashlib.md5(
        (platform.machine() + ":" + _host_feature_lines()).encode()
    ).hexdigest()[:8]


_DEFAULT = _BASE + "." + _machine_tag()

#: sentinel recording which host populated a cache dir (the scrub key)
_FINGERPRINT_NAME = "HOST_FINGERPRINT"


def _host_fingerprint() -> str:
    import hashlib
    import platform

    return hashlib.md5(
        (platform.machine() + ":" + _host_feature_lines()).encode()
    ).hexdigest()


def scrub_on_host_mismatch(path: str) -> bool:
    """Drop a persistent-cache dir's entries when its recorded host
    fingerprint doesn't match THIS host; stamp the current fingerprint
    either way. Returns whether a scrub happened.

    The dir-name tag can't protect a pinned dir ($FDBTPU_COMPILE_CACHE)
    or a dir baked into a migrating container: loading another
    machine's XLA:CPU AOT entries spams machine-feature-mismatch errors
    on stderr — which polluted the multichip lane's JSON `tail`
    (MULTICHIP_r05) — and risks SIGILL. Scrubbing trades one warm cache
    for a clean, safe run on the new host."""
    marker = os.path.join(path, _FINGERPRINT_NAME)
    want = _host_fingerprint()
    try:
        with open(marker) as f:
            have = f.read().strip()
    except OSError:
        have = None
    try:
        entries = [n for n in os.listdir(path) if n != _FINGERPRINT_NAME]
    except OSError:
        entries = []
    scrubbed = False
    # An UNSTAMPED dir that already holds entries cannot be proven
    # local: a container baked before the marker existed carries
    # another machine's AOT entries with no stamp at all — exactly the
    # migrating scenario this scrub exists for. Conservatively scrub
    # (one re-warm beats a SIGILL risk); an empty dir just gets
    # stamped.
    if (have is not None and have != want) or (have is None and entries):
        import shutil

        for name in os.listdir(path):
            if name == _FINGERPRINT_NAME:
                continue
            victim = os.path.join(path, name)
            try:
                if os.path.isdir(victim):
                    shutil.rmtree(victim, ignore_errors=True)
                else:
                    os.remove(victim)
            except OSError:
                pass  # a straggler entry keeps its warning; never fatal
        scrubbed = True
        from foundationdb_tpu.utils.trace import SEV_WARN, TraceEvent

        TraceEvent("CompileCacheScrubbed", severity=SEV_WARN).detail(
            "Path", path
        ).detail("RecordedFingerprint", have or "unstamped").detail(
            "HostFingerprint", want
        ).log()
    if have != want:
        try:
            with open(marker, "w") as f:
                f.write(want + "\n")
        except OSError:
            pass
    return scrubbed


def enable(path: str | None = None) -> str:
    """Turn on the persistent compilation cache; returns the cache dir.

    Safe to call multiple times and before/after backend init (the cache
    is consulted at compile time, not backend-init time). Also arms the
    compile-observability listeners (`instrument()`), so every enabled
    process carries hit/miss counters and compile seconds in `stats()`.
    A dir whose recorded host fingerprint mismatches this machine is
    scrubbed first (see `scrub_on_host_mismatch`) — stale cross-host
    XLA:CPU AOT entries must never load.
    """
    import jax

    path = path or os.environ.get("FDBTPU_COMPILE_CACHE", _DEFAULT)
    os.makedirs(path, exist_ok=True)
    scrub_on_host_mismatch(path)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: the kernel's many specializations are each well
    # over the default thresholds anyway, and tiny entries are harmless.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    instrument()
    return path


# ---------------------------------------------------------------------------
# Compile observability (ISSUE 10): JAX emits monitoring events for
# persistent-cache hits/misses and backend-compile durations; this
# module aggregates them into one process-global stats block that
# KernelStageMetrics.qos() / cluster_status() / the perf ledger read.
# Process-global on purpose — the XLA compiler and its cache are too.
# These counters are wall-clock/host-dependent and deliberately stay
# OUT of every CounterCollection the deterministic trace flush ships.

_stats_lock = threading.Lock()
_stats = {
    "cache_hits": 0,
    "cache_misses": 0,
    "backend_compiles": 0,
    "compile_seconds_total": 0.0,
    "last_compile_seconds": 0.0,
}
#: explicit per-signature compile seconds (warm-compile paths that know
#: what they compiled record here; the monitoring listener only knows
#: durations, not signatures)
_signatures: dict[str, float] = {}
_instrumented = False

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_event(event: str, *a, **kw) -> None:
    if event == _HIT_EVENT:
        with _stats_lock:
            _stats["cache_hits"] += 1
    elif event == _MISS_EVENT:
        with _stats_lock:
            _stats["cache_misses"] += 1
        code_probe(True, "perf.compile_cache_miss")


def _on_duration(event: str, duration: float, *a, **kw) -> None:
    if event.endswith("backend_compile_duration"):
        with _stats_lock:
            _stats["backend_compiles"] += 1
            _stats["compile_seconds_total"] += float(duration)
            _stats["last_compile_seconds"] = float(duration)


def instrument() -> bool:
    """Register the jax.monitoring listeners (idempotent). Returns
    whether the listeners are armed — an older/newer JAX without the
    monitoring API degrades to zeros, never an error."""
    global _instrumented
    if _instrumented:
        return True
    try:
        from jax import monitoring

        # resolve BOTH registrars before registering either: failing
        # between the two would leave _instrumented False and a later
        # enable() would register _on_event twice (double counts)
        reg = monitoring.register_event_listener
        reg_duration = monitoring.register_event_duration_secs_listener
    except Exception:
        return False
    _instrumented = True  # before the calls: never re-register
    reg(_on_event)
    reg_duration(_on_duration)
    return True


def record_compile(signature: str, seconds: float) -> None:
    """Per-signature compile seconds, recorded by the code paths that
    know WHAT they compiled (ResolverRole warm compile, bench warm
    loops). Keeps the most recent duration per signature."""
    with _stats_lock:
        _signatures[signature] = float(seconds)


def stats() -> dict:
    """One snapshot: cache hit/miss counters, backend-compile count and
    seconds, and the per-signature compile-seconds map."""
    with _stats_lock:
        out = dict(_stats)
        out["per_signature_compile_seconds"] = dict(_signatures)
    return out


def reset_stats() -> None:
    """Test hook: zero the process-global counters."""
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0.0 if isinstance(_stats[k], float) else 0
        _signatures.clear()
