"""Persistent XLA compilation cache (VERDICT r1 task 10).

The driver re-runs bench.py in a fresh process every round; without a
persistent cache each run re-pays the full trace+compile of the resolver
kernel (137s at 64K-txn shapes in BENCH_r01.json). JAX's persistent
cache keys on (HLO, compile options, backend version), so a warm cache
drops that to de/serialization time.
"""

from __future__ import annotations

import os

_DEFAULT = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_compile_cache")


def enable(path: str | None = None) -> str:
    """Turn on the persistent compilation cache; returns the cache dir.

    Safe to call multiple times and before/after backend init (the cache
    is consulted at compile time, not backend-init time).
    """
    import jax

    path = path or os.environ.get("FDBTPU_COMPILE_CACHE", _DEFAULT)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: the kernel's many specializations are each well
    # over the default thresholds anyway, and tiny entries are harmless.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
