"""Live resource census: the runtime half of the ownership gate.

The `res.*` flowcheck family proves no code PATH leaks a resource; this
module proves no RUN did. Three cheap process-wide gauges:

* **fds** — live file descriptors, read straight off /proc/self/fd
  (the kernel's own census; no bookkeeping to drift).
* **connections / servers** — per-process RpcConnection/RpcServer
  gauges, bumped at activation and dropped at release by the transport
  itself (wire/transport.py), so the count is the transport's truth,
  not a parallel ledger.
* **tasks** — the Scheduler's live-task count (`run_loop_stats()
  ["tasks_live"]`: incremented at Task construction, retired exactly
  once at the terminal done-set).

The gate is a pre/post compare: snapshot before work, drain, snapshot
after — growth in any gauge is a leak, named. `run_seed(census=True)`
and the chaos/elasticity drills fail on it, which is the FoundationDB
two-layer discipline (static pass + simulation check) applied to
resource ownership.

Census reads NEVER land in traces: soak's determinism contract digests
trace output, and gauge values depend on wall-clock scheduling of real
I/O. The 20-seed census determinism sweep (tests/test_census.py) pins
that the armed gate leaves run signatures bit-identical.
"""

from __future__ import annotations

import os
from typing import Optional


class Gauge:
    """One process-wide up/down counter. Deliberately not thread-safe:
    every mutator runs on the owning process's event loop."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self) -> None:
        self.value += 1

    def dec(self) -> None:
        self.value -= 1


#: live activated RpcConnections in this process (client side)
CONNECTIONS = Gauge("connections")
#: live started RpcServers in this process
SERVERS = Gauge("servers")


def live_fds() -> int:
    """Count of open file descriptors, from /proc/self/fd. Returns -1
    where /proc is unavailable (non-Linux) — callers treat a negative
    census as "not measurable", never as a leak."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def snapshot(sched=None) -> dict:
    """One census reading: {fds, connections, servers, tasks}. Pass the
    owning Scheduler to include its live-task count (0 without one)."""
    tasks = 0
    if sched is not None:
        tasks = int(sched.run_loop_stats().get("tasks_live", 0))
    return {
        "fds": live_fds(),
        "connections": CONNECTIONS.value,
        "servers": SERVERS.value,
        "tasks": tasks,
    }


def growth(pre: dict, post: dict, *,
           ignore: Optional[set] = None) -> list[str]:
    """Gauges that grew between two snapshots: the leak report. A
    metric absent from either snapshot, or negative (unmeasurable) in
    either, is skipped; equality and shrinkage are clean."""
    leaks: list[str] = []
    for key in sorted(pre.keys() & post.keys()):
        if ignore and key in ignore:
            continue
        a, b = pre[key], post[key]
        if a < 0 or b < 0:
            continue
        if b > a:
            leaks.append(f"{key} grew {a} -> {b}")
    return leaks


def check_drained(pre: dict, post: dict, *, label: str = "census",
                  ignore: Optional[set] = None) -> None:
    """Raise RuntimeError naming every gauge that failed to return to
    its pre-run baseline — the census gate the drills arm."""
    leaks = growth(pre, post, ignore=ignore)
    if leaks:
        raise RuntimeError(
            f"{label}: resource census did not return to baseline "
            f"after drain: {'; '.join(leaks)}"
        )
