from foundationdb_tpu.runtime.flow import (
    ActorCancelled,
    Future,
    FutureStream,
    Notified,
    Promise,
    PromiseStream,
    Scheduler,
    TaskPriority,
)

__all__ = [
    "ActorCancelled",
    "Future",
    "FutureStream",
    "Notified",
    "Promise",
    "PromiseStream",
    "Scheduler",
    "TaskPriority",
]
