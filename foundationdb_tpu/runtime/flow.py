"""A deterministic single-threaded actor runtime: the Flow/Net2 analog.

The reference's entire architecture rests on one idea: every role is an
actor (a cooperative coroutine) on a single-threaded prioritized run loop
(`flow/Net2.actor.cpp:1421` run loop; `flow/flow.h` Future/Promise), and
the same code runs under a simulated clock for deterministic testing
(`fdbrpc/sim2.actor.cpp`). This module provides the same contract in
Python, TPU-era style:

* `Scheduler` — the run loop. In `sim` mode time is virtual: when no task
  is runnable the clock jumps to the next timer, so a whole cluster of
  actors runs deterministically in one OS process, reproducible from a
  seed (the Sim2 strategy). In real mode timers use the wall clock.
* `Future`/`Promise` — single-assignment async values (`flow/flow.h`
  SAV). Awaitable from any actor coroutine.
* `PromiseStream`/`FutureStream` — multi-value channels (RPC endpoints).
* `Notified` — a monotonically increasing value with `when_at_least`,
  mirroring NotifiedVersion, the primitive behind the resolver/proxy
  version chains (`fdbserver/Resolver.actor.cpp:283`).
* Task ordering is strict: (time, -priority, sequence). Two runs with the
  same seed and the same spawn order execute identically — determinism
  IS the race detector here, as in the reference (SURVEY.md §5.2).

Actors are plain `async def` functions awaiting these primitives; the
scheduler drives the coroutines directly (no asyncio), so the event order
is fully owned by this module.
"""

from __future__ import annotations

import heapq
import time as _time
from typing import Any, Awaitable, Callable, Generator, Iterable, Optional

from foundationdb_tpu.utils.probes import code_probe, declare

declare("runtime.slow_task")


class ActorCancelled(BaseException):
    """Raised inside an actor when its task is cancelled (actor_cancelled)."""


class TaskPriority:
    """A small slice of the reference's priority lattice (TaskPriority.h)."""

    Max = 1000000
    RunLoop = 30000
    DefaultDelay = 7010
    DefaultEndpoint = 7000
    ProxyCommit = 8540
    ProxyResolverReply = 8547
    ResolutionMetrics = 8700
    Low = 2000
    Zero = 0


class Future:
    """Single-assignment future. Await it from an actor coroutine."""

    __slots__ = ("_done", "_value", "_error", "_callbacks",
                 "_error_observed", "_consumed", "_members")

    def __init__(self):
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._callbacks: list[Callable[[Future], None]] = []
        #: set once SOMETHING consumed the error (get() raised it, or the
        #: consumed aggregate of a combinator covered it) — the
        #: scheduler's unhandled-error ledger filters on this, so a
        #: fire-and-forget crash awaited later does not count as escaped
        #: (the round-5 soak printed 264 tracebacks for exactly that
        #: shape and still passed green)
        self._error_observed = False
        #: the outcome of this future was delivered to someone (get()
        #: returned or raised) — combinator member observation keys off
        #: THIS, so a dropped `any_of(...)` aggregate does not silently
        #: consume its members' errors
        self._consumed = False
        #: set by all_of/any_of on the aggregate: member futures whose
        #: errors are delegated to it once it is consumed
        self._members: Optional[list["Future"]] = None

    # -- producer side ---------------------------------------------------

    def _set(self, value: Any) -> None:
        if self._done:
            raise RuntimeError("future already set")
        self._done = True
        self._value = value
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def _set_error(self, err: BaseException) -> None:
        if self._done:
            raise RuntimeError("future already set")
        self._done = True
        self._error = err
        if self._consumed:
            # consumed BEFORE the error arrived (abandoned by a
            # cancelled awaiter): the error is covered by that consumer
            self._error_observed = True
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    # -- consumer side ---------------------------------------------------

    @property
    def is_ready(self) -> bool:
        return self._done

    @property
    def is_error(self) -> bool:
        return self._done and self._error is not None

    def _mark_consumed(self) -> None:
        """This future's outcome reached a consumer. Member errors
        (combinators) become observed HERE — racing/fanning futures and
        consuming the aggregate is handling the losers too (two tlog
        replicas raising on one epoch lock: first error wins the await,
        the sibling's is delegated) — but only here: an aggregate nobody
        ever consumes keeps its members' errors escaped."""
        if self._consumed:
            return
        self._consumed = True
        if self._error is not None:
            self._error_observed = True
        if self._members:
            for m in self._members:
                if m.is_error:
                    m._error_observed = True

    def get(self) -> Any:
        if not self._done:
            raise RuntimeError("future not ready")
        self._mark_consumed()
        if self._error is not None:
            raise self._error
        return self._value

    def add_done_callback(self, cb: Callable[[Future], None]) -> None:
        if self._done:
            cb(self)
        else:
            self._callbacks.append(cb)

    def __await__(self) -> Generator["Future", None, Any]:
        if not self._done:
            yield self
        return self.get()


class Promise:
    """Producer handle for a Future (reference Promise<T>)."""

    __slots__ = ("future", "tag", "debug_id", "span_ctx", "grv_start")

    def __init__(self):
        self.future = Future()
        self.tag = None  # optional transaction tag (GRV throttling)
        self.debug_id = None  # commit-path tracing (GRV micro-events)
        self.span_ctx = None  # client span context (GRV batch span parent)
        self.grv_start = 0.0  # enqueue time for the GRV latency bands

    def send(self, value: Any = None) -> None:
        self.future._set(value)

    def send_error(self, err: BaseException) -> None:
        self.future._set_error(err)

    @property
    def is_set(self) -> bool:
        return self.future.is_ready


class FutureStream:
    """Consumer end of a PromiseStream."""

    __slots__ = ("_queue", "_waiters")

    def __init__(self):
        self._queue: list[Any] = []
        self._waiters: list[Future] = []

    def next(self) -> Future:
        f = Future()
        if self._queue:
            f._set(self._queue.pop(0))
        else:
            self._waiters.append(f)
        return f

    def try_next(self):
        """(True, value) if an item is queued, else (False, None) — no
        future, no suspension. Drain loops use this so a value can never
        sit inside a waiter future orphaned by task cancellation (the
        send()-delivers-into-waiter model means a consumer cancelled
        between delivery and resumption silently loses the item)."""
        if self._queue:
            return True, self._queue.pop(0)
        return False, None

    def is_empty(self) -> bool:
        return not self._queue


class PromiseStream:
    """Multi-value channel; the shape of an RPC request stream."""

    __slots__ = ("stream",)

    def __init__(self):
        self.stream = FutureStream()

    def send(self, value: Any) -> None:
        s = self.stream
        while s._waiters:
            w = s._waiters.pop(0)
            if not w.is_ready:  # waiter may have been cancelled via choose
                w._set(value)
                return
        s._queue.append(value)


class Notified:
    """Monotone value with when_at_least — NotifiedVersion.

    The backbone of the version chains: the resolver waits
    `version.when_at_least(req.prev_version)` before computing
    (fdbserver/Resolver.actor.cpp:283), the proxy chains batches the same
    way (CommitProxyServer.actor.cpp:822-853).
    """

    def __init__(self, value=0):
        self._value = value
        self._waiters: list[tuple[Any, Future]] = []  # (threshold, future)

    def get(self):
        return self._value

    def set(self, value) -> None:
        if value < self._value:
            raise ValueError(f"Notified must not decrease: {value} < {self._value}")
        self._value = value
        still = []
        for threshold, fut in self._waiters:
            if fut.is_ready:
                continue
            if threshold <= value:
                fut._set(value)
            else:
                still.append((threshold, fut))
        self._waiters = still

    def when_at_least(self, threshold) -> Future:
        f = Future()
        if threshold <= self._value:
            f._set(self._value)
        else:
            self._waiters.append((threshold, f))
        return f

    def num_waiting(self) -> int:
        return sum(1 for _, f in self._waiters if not f.is_ready)


class Trigger:
    """An edge-triggered signal (AsyncTrigger): on_trigger wakes all waiters."""

    def __init__(self):
        self._waiters: list[Future] = []

    def on_trigger(self) -> Future:
        f = Future()
        self._waiters.append(f)
        return f

    def trigger(self) -> None:
        ws, self._waiters = self._waiters, []
        for f in ws:
            if not f.is_ready:
                f._set(None)


class InterleavingAuditor:
    """Runtime side of the `flow.*` rules: lost-update detection on
    shared objects across actor yield points.

    The static pass (analysis/rules_flow.py) proves shapes; this
    auditor catches the *executions*: an actor reads a tracked
    (object, key) slot in one step, a DIFFERENT actor writes that slot
    in a later step, and the first actor then writes it based on the
    stale read — the Eraser-lesson RMW interleaving, adapted to a
    cooperative single-threaded scheduler where the only possible race
    is across a wait(). Ordering discipline is re-reading: an actor
    that re-reads the slot after the foreign write (the handoff idiom)
    updates its pending read and is clean; an actor that writes from a
    pre-wait value is flagged whether or not a future "ordered" its
    resumption, because the value it wrote is stale either way.

    Pure observation: tracking changes no behavior and no schedule, so
    audited runs stay seed-deterministic. Objects opt in via
    `AuditedDict` (or direct record_read/record_write calls); code that
    never wraps anything pays nothing.
    """

    MAX_CONFLICTS = 64

    def __init__(self):
        self.step = 0              # global actor-step counter
        self.current: Optional[str] = None  # actor name mid-step
        #: (label, key) -> actor name -> step of last unconsumed read
        self._reads: dict[tuple, dict[str, int]] = {}
        #: (label, key) -> (actor name, step) of the last write
        self._last_write: dict[tuple, tuple[str, int]] = {}
        self.conflicts: list[dict] = []

    # -- step boundaries (driven by Task._step) ---------------------------

    def begin_step(self, name: str) -> None:
        self.step += 1
        self.current = name

    def end_step(self) -> None:
        self.current = None

    # -- access recording --------------------------------------------------

    def record_read(self, label: str, key) -> None:
        if self.current is None:
            return  # setup/verify code outside any actor step
        self._reads.setdefault((label, key), {})[self.current] = self.step

    def record_write(self, label: str, key) -> None:
        me = self.current
        if me is None:
            return
        # `key` and the whole-object wildcard "*" address the same
        # slot; a wildcard WRITE (clear) addresses every slot of the
        # label, so it probes all recorded keys — a stale scan followed
        # by clear() loses foreign per-key writes just as surely as a
        # per-key overwrite would
        if key == "*":
            # sorted: the first conflicting key wins the report, and
            # "first" must not depend on PYTHONHASHSEED (each run's
            # failure output is part of its reproducibility contract)
            probe = tuple(sorted(
                (k for (lb, k) in set(self._reads) | set(self._last_write)
                 if lb == label),
                key=repr,  # keys may mix str/bytes/ints with the "*"
                #            sentinel: repr orders across types, so the
                #            winning conflict stays hash-seed-independent
            )) or ("*",)
        else:
            probe = (key, "*")
        my_read = None
        for k2 in probe:
            r = self._reads.get((label, k2), {}).get(me)
            if r is not None and (my_read is None or r > my_read):
                my_read = r
        if my_read is not None:
            for k2 in probe:
                lw = self._last_write.get((label, k2))
                if lw is None:
                    continue
                w_actor, w_step = lw
                if w_actor != me and my_read < w_step:
                    if len(self.conflicts) < self.MAX_CONFLICTS:
                        self.conflicts.append({
                            "label": label, "key": key,
                            "actor": me, "read_step": my_read,
                            "writer": w_actor, "write_step": w_step,
                            "step": self.step,
                        })
                    break
        # this write consumes our pending read — BOTH probe slots: a
        # wildcard scan that fed this write is consumed by it too, or a
        # single stale scan would re-flag against every later write —
        # and becomes the slot's latest write
        for k2 in probe:
            self._reads.get((label, k2), {}).pop(me, None)
        self._last_write[(label, key)] = (me, self.step)


class AuditedDict:
    """A dict proxy reporting per-key access to the scheduler's
    interleaving auditor. With no auditor installed the overhead is one
    attribute check per operation — cheap enough to leave in soak
    workloads permanently. Aggregate operations (iteration, len, bool,
    items) read — and clear() writes — the wildcard slot "*", which
    conflicts with every per-key access."""

    __slots__ = ("_d", "_sched", "_label")

    def __init__(self, sched: "Scheduler", label: str, initial=None):
        self._d = dict(initial or {})
        self._sched = sched
        self._label = label

    def _read(self, key) -> None:
        a = self._sched.auditor
        if a is not None:
            a.record_read(self._label, key)

    def _write(self, key) -> None:
        a = self._sched.auditor
        if a is not None:
            a.record_write(self._label, key)

    def __getitem__(self, key):
        self._read(key)
        return self._d[key]

    def __setitem__(self, key, value) -> None:
        self._write(key)
        self._d[key] = value

    def __delitem__(self, key) -> None:
        self._read(key)  # presence check is an observation
        if key in self._d:
            self._write(key)  # only a real removal is a write
        del self._d[key]

    def __contains__(self, key) -> bool:
        self._read(key)
        return key in self._d

    def get(self, key, default=None):
        self._read(key)
        return self._d.get(key, default)

    def setdefault(self, key, default=None):
        self._read(key)
        if key not in self._d:
            self._write(key)
        return self._d.setdefault(key, default)

    def pop(self, key, *default):
        self._read(key)  # presence check is an observation
        if key in self._d:
            # only a real removal is a write: pop(absent, default)
            # mutates nothing, and a phantom last_write here would
            # frame this actor as the writer in a later conflict
            self._write(key)
        return self._d.pop(key, *default)

    def update(self, other=(), **kw) -> None:
        items = dict(other, **kw)
        for k in items:
            self._write(k)
        self._d.update(items)

    def clear(self) -> None:
        self._write("*")
        self._d.clear()

    def keys(self):
        self._read("*")
        return self._d.keys()

    def values(self):
        self._read("*")
        return self._d.values()

    def items(self):
        self._read("*")
        return self._d.items()

    def __iter__(self):
        self._read("*")
        return iter(self._d)

    def __len__(self) -> int:
        self._read("*")
        return len(self._d)

    def __bool__(self) -> bool:
        self._read("*")
        return bool(self._d)

    def __eq__(self, other):
        self._read("*")
        return self._d == (other._d if isinstance(other, AuditedDict)
                           else other)

    def __repr__(self) -> str:
        return f"AuditedDict({self._label!r}, {self._d!r})"


class Task:
    """A spawned actor: drives a coroutine over Futures."""

    __slots__ = ("_coro", "_sched", "_priority", "done", "_cancelled",
                 "_name", "_waiting", "_retired")

    def __init__(self, coro, sched: "Scheduler", priority: int, name: str = ""):
        self._coro = coro
        self._sched = sched
        self._priority = priority
        self._cancelled = False
        self._name = name or getattr(coro, "__name__", "actor")
        #: the future this actor is currently suspended on — cancelling
        #: the actor ABANDONS it (the reference's drop-the-future
        #: semantics), which counts as consumption for the unhandled
        #: ledger: a tlog replica erroring after recovery cancelled the
        #: batch actor awaiting it is not an "escaped" error
        self._waiting: Optional[Future] = None
        self.done = Future()
        #: live-task census: retired exactly once, at the terminal
        #: done._set/_set_error — NOT via add_done_callback, which would
        #: defeat the `not done._callbacks` fire-and-forget crash print
        self._retired = False
        sched._tasks_live += 1

    def _retire(self) -> None:
        if not self._retired:
            self._retired = True
            self._sched._tasks_live -= 1

    def cancel(self) -> None:
        """Cancel the actor (reference: dropping the last Future reference)."""
        if self.done.is_ready or self._cancelled:
            return
        self._cancelled = True
        self._sched._schedule(0.0, self._priority, self._step_throw)

    def _step_throw(self) -> None:
        if self.done.is_ready:
            return
        if self._waiting is not None:
            # cancellation abandons the pending await: its (possibly
            # later) error is consumed by the cancel, not escaped
            self._waiting._mark_consumed()
            self._waiting = None
        auditor = self._sched.auditor
        if auditor is not None:
            # the cancel throw still runs actor code (finally blocks
            # may touch audited shared state): it is a step too
            auditor.begin_step(self._name)
        try:
            self._step_throw_inner()
        finally:
            if auditor is not None:
                auditor.end_step()

    def _step_throw_inner(self) -> None:
        try:
            self._coro.throw(ActorCancelled())
        except (StopIteration, ActorCancelled):
            self.done._set_error(ActorCancelled())
            self._retire()
            return
        except BaseException as e:  # actor swallowed the cancel and raised
            self.done._set_error(e)
            self._retire()
            return
        # Actor caught the cancellation and kept awaiting: treat as done.
        self.done._set_error(ActorCancelled())
        self._retire()

    def _step(self, fut: Optional[Future]) -> None:
        if self.done.is_ready or self._cancelled:
            return
        # slow-task profiling measures WALL time on purpose: it reports
        # a step blocking the real run loop, not virtual time
        t0 = _time.perf_counter()  # flowcheck: ignore[determinism]
        auditor = self._sched.auditor
        if auditor is not None:
            auditor.begin_step(self._name)
        try:
            self._step_inner(fut)
        finally:
            if auditor is not None:
                auditor.end_step()
            sched = self._sched
            elapsed = _time.perf_counter() - t0  # flowcheck: ignore[determinism]
            # run-loop utilization accounting (Net2's networkMetrics
            # priority-busy counters): every step's wall time lands in
            # the busy total — one add on a float already in hand
            sched._busy_wall += elapsed
            sched._steps += 1
            # fast path: two clock reads + one compare per step; the
            # full per-actor profile is opt-in (Scheduler(profile=True))
            if sched._profile or elapsed > sched.SLOW_TASK_THRESHOLD:
                sched._note_step(self._name, elapsed)

    def _step_inner(self, fut: Optional[Future]) -> None:
        self._waiting = None  # resumed: no longer suspended on `fut`
        try:
            if fut is not None and fut.is_error:
                fut._mark_consumed()  # delivered into the actor
                waited = self._coro.throw(fut._error)
            else:
                # The awaited value is delivered by Future.__await__'s own
                # `return self.get()`; send just resumes the coroutine.
                waited = self._coro.send(None)
        except StopIteration as stop:
            self.done._set(stop.value)
            self._retire()
            return
        except ActorCancelled:
            self.done._set_error(ActorCancelled())
            self._retire()
            return
        except BaseException as e:
            if not self.done._callbacks:
                # Fire-and-forget actor crashed with nobody awaiting: surface
                # it (a silent death here stalls whatever chains on the
                # actor's side effects — the hardest deadlock to debug).
                import sys
                import traceback

                print(
                    f"[flow] unhandled error in actor {self._name!r}:",
                    file=sys.stderr,
                )
                traceback.print_exception(e, file=sys.stderr)
            # ledger every non-cancel crash; entries whose done future is
            # later consumed (awaited / get()) drop out of
            # Scheduler.unhandled_errors() — what remains truly escaped.
            # Amortized bound: once the ledger is large, shed entries
            # already observed (routine handled chaos must not pin every
            # exception+traceback for the scheduler's lifetime)
            ledger = self._sched._maybe_unhandled
            if len(ledger) >= 256:
                ledger[:] = [
                    ent for ent in ledger if not ent[2]._error_observed
                ]
                if len(ledger) >= 1024:
                    # hard cap for long-lived real-mode schedulers where
                    # nobody drains the ledger: shed the oldest escapes
                    # (each pins an exception + traceback frames) — any
                    # remaining entry still fails a soak seed
                    del ledger[:512]
            ledger.append((self._name, e, self.done))
            self.done._set_error(e)
            self._retire()
            return
        if not isinstance(waited, Future):
            raise TypeError(f"actor awaited non-Future {waited!r}")
        self._waiting = waited
        waited.add_done_callback(
            lambda f: self._sched._schedule(0.0, self._priority, lambda: self._step(f))
        )

    def __await__(self):
        return self.done.__await__()


class Scheduler:
    """The single-threaded prioritized run loop (Net2::run / Sim2).

    sim=True — virtual clock: the loop never sleeps, it advances `now` to
    the next timer when idle. This is what makes whole-cluster tests
    deterministic and fast (the Sim2 design, fdbrpc/sim2.actor.cpp:977).
    sim=False — timers wait on the wall clock (time.monotonic).
    """

    #: one actor step blocking the loop longer than this (WALL seconds)
    #: is a slow task: the single-threaded run loop serves nothing else
    #: meanwhile (flow/Net2.actor.cpp:1462 checkForSlowTask)
    SLOW_TASK_THRESHOLD = 0.05

    def __init__(self, *, sim: bool = True, start_time: float = 0.0,
                 profile: bool = False, audit: bool = False,
                 perturb_seed: Optional[int] = None):
        self.sim = sim
        self._profile = profile
        # real mode anchors the clock to the wall on purpose
        self._now = start_time if sim else _time.monotonic()  # flowcheck: ignore[determinism]
        self._seq = 0
        #: opt-in interleaving auditor (lost updates across yield
        #: points on AuditedDict-tracked shared objects)
        self.auditor: Optional[InterleavingAuditor] = (
            InterleavingAuditor() if audit else None
        )
        #: schedule perturbation: a seeded tie-break among EQUALLY
        #: RUNNABLE entries — same due time, same priority. Any such
        #: order is a legal schedule; a correctness property that only
        #: holds under FIFO tie order is a race. None = FIFO (the
        #: historical order, byte-identical to pre-perturbation runs).
        self._perturb_state: Optional[int] = (
            None if perturb_seed is None
            else (perturb_seed ^ 0x9E3779B97F4A7C15) & ((1 << 64) - 1)
        )
        #: (actor name, error, done future) for every non-cancel actor
        #: crash; see unhandled_errors()
        self._maybe_unhandled: list[tuple[str, BaseException, Future]] = []
        # (due, -priority, tie, seq, fn): `tie` is 0 under FIFO order
        # and a seeded draw under perturbation; `seq` keeps comparisons
        # off `fn` either way
        self._heap: list[tuple[float, int, int, int, Callable[[], None]]] = []
        self._running = False
        #: per-actor-name step profile: [steps, total_wall_s, max_wall_s]
        #: — the ActorLineageProfiler collapsed to what a single-threaded
        #: deterministic loop can measure honestly. With profile=True
        #: EVERY step is recorded (no sampling thread required); by
        #: default only steps over SLOW_TASK_THRESHOLD land here, so
        #: step counts/totals for fast actors are intentionally absent
        self.actor_profile: dict[str, list] = {}
        self.slow_tasks: list[tuple[str, float]] = []
        # run-loop utilization (the Net2 networkMetrics busy fraction):
        # WALL seconds spent inside actor steps vs wall seconds since
        # construction. Wall-clock on purpose — it measures how busy
        # this OS process's loop is, which virtual time cannot; status
        # readers surface it, traced simulation output never does (the
        # trace-digest determinism contract).
        self._busy_wall = 0.0
        self._steps = 0
        self._slow_task_total = 0
        #: live-task census (incremented at Task construction, retired
        #: at its terminal done-set): the scheduler half of the
        #: resource census gate — a drained run returns this to its
        #: pre-run baseline or the census gate fails the seed
        self._tasks_live = 0
        self._wall_anchor = _time.perf_counter()  # flowcheck: ignore[determinism]

    def run_loop_stats(self) -> dict:
        """Saturation view of the run loop: busy fraction, step count,
        slow-task ledger summary. The "~40% idle parent loop" class of
        diagnosis (PIPELINE_r07) reads directly off `utilization`
        instead of being reconstructed from traces after the fact."""
        wall = _time.perf_counter() - self._wall_anchor  # flowcheck: ignore[determinism]
        slow_by_actor: dict[str, int] = {}
        for name, _s in self.slow_tasks:
            slow_by_actor[name] = slow_by_actor.get(name, 0) + 1
        return {
            "utilization": (self._busy_wall / wall) if wall > 0 else 0.0,
            "busy_seconds": self._busy_wall,
            "wall_seconds": wall,
            "steps": self._steps,
            "tasks_live": self._tasks_live,
            "slow_tasks": self._slow_task_total,
            "slow_tasks_by_actor": dict(
                sorted(slow_by_actor.items(), key=lambda kv: -kv[1])[:10]
            ),
        }

    def _note_step(self, name: str, elapsed: float) -> None:
        st = self.actor_profile.get(name)
        if st is None:
            st = self.actor_profile[name] = [0, 0.0, 0.0]
        st[0] += 1
        st[1] += elapsed
        if elapsed > st[2]:
            st[2] = elapsed
        if elapsed > self.SLOW_TASK_THRESHOLD:
            self._slow_task_total += 1
            if len(self.slow_tasks) >= 256:  # bounded, like trace rolls
                del self.slow_tasks[:128]
            code_probe(True, "runtime.slow_task")
            self.slow_tasks.append((name, elapsed))
            from foundationdb_tpu.utils.trace import SEV_WARN, TraceEvent

            TraceEvent("SlowTask", severity=SEV_WARN).detail(
                "Actor", name
            ).detail("Ms", round(elapsed * 1e3, 1)).log()

    def profile_top(self, n: int = 10) -> list[tuple[str, int, float, float]]:
        """Top actors by cumulative wall time in their steps: (name,
        steps, total_s, max_step_s) — the profiler surface the reference
        gets from ActorLineageProfiler sampling."""
        rows = [
            (name, st[0], st[1], st[2])
            for name, st in self.actor_profile.items()
        ]
        rows.sort(key=lambda r: -r[2])
        return rows[:n]

    # -- unhandled actor errors -------------------------------------------

    def unhandled_errors(self) -> list[tuple[str, BaseException]]:
        """Actor crashes nothing ever consumed: the error reached the
        Task's done future and NO ONE awaited/get() it (directly or via
        a combinator). The reference makes this class structurally loud
        (an ACTOR error lands in its Future; the simulator crashes on
        unhandled ones) — soak fails a seed on any entry here."""
        return [
            (name, err)
            for name, err, fut in self._maybe_unhandled
            if not fut._error_observed
        ]

    def clear_unhandled(self) -> None:
        self._maybe_unhandled.clear()

    # -- interleaving audit ------------------------------------------------

    def audit_conflicts(self) -> list[dict]:
        """Lost-update conflicts the interleaving auditor observed on
        tracked shared objects (empty when auditing is off). Soak fails
        a seed on any entry, like the unhandled-error ledger."""
        return [] if self.auditor is None else list(self.auditor.conflicts)

    # -- time -------------------------------------------------------------

    def now(self) -> float:
        return self._now

    def _tie(self) -> int:
        """Next tie-break value: 0 (FIFO via seq) unless perturbing, in
        which case a splitmix64 draw — deterministic per perturb_seed,
        so a perturbed schedule is itself exactly reproducible."""
        if self._perturb_state is None:
            return 0
        m = (1 << 64) - 1
        self._perturb_state = (
            self._perturb_state + 0x9E3779B97F4A7C15
        ) & m
        z = self._perturb_state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & m
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & m
        return z ^ (z >> 31)

    def _schedule(self, delay: float, priority: int, fn: Callable[[], None]) -> None:
        self._seq += 1
        due = self._now + max(0.0, delay)
        heapq.heappush(
            self._heap, (due, -priority, self._tie(), self._seq, fn)
        )

    def delay(self, seconds: float, priority: int = TaskPriority.DefaultDelay) -> Future:
        f = Future()
        self._schedule(seconds, priority, lambda: None if f.is_ready else f._set(None))
        return f

    # -- actors -----------------------------------------------------------

    def spawn(self, coro, priority: int = TaskPriority.DefaultEndpoint,
              name: str = "") -> Task:
        task = Task(coro, self, priority, name)
        self._schedule(0.0, priority, lambda: task._step(None))
        return task

    # -- run loop ---------------------------------------------------------

    def run_until(self, fut: Future, *, max_time: float = float("inf")) -> Any:
        """Drive the loop until `fut` resolves (or the virtual clock passes
        max_time / the task queue drains)."""
        self._running = True
        try:
            while not fut.is_ready:
                if not self._heap:
                    raise RuntimeError("deadlock: run queue drained, future unresolved")
                due, negpri, tie, seq, fn = heapq.heappop(self._heap)
                if due > self._now:
                    if due > max_time:
                        # Put the event back: a later run must still see it.
                        heapq.heappush(self._heap, (due, negpri, tie, seq, fn))
                        raise TimeoutError(
                            f"virtual clock passed {max_time} awaiting future"
                        )
                    if self.sim:
                        self._now = due
                    else:
                        # real mode: timers genuinely wait on the wall
                        _time.sleep(max(0.0, due - _time.monotonic()))  # flowcheck: ignore[determinism]
                        self._now = _time.monotonic()  # flowcheck: ignore[determinism]
                fn()
            return fut.get()
        finally:
            self._running = False

    def run_for(self, seconds: float) -> None:
        """Run the loop for a span of (virtual) time."""
        self.run_until(self.delay(seconds))


# -- combinators ----------------------------------------------------------


def all_of(futures: Iterable[Future]) -> Future:
    """waitForAll: resolves with the list of values (first error wins).

    Member-error observation is delegated to the aggregate: once `out`
    is consumed, every member error (including a sibling failing AFTER
    the first error won — two tlog replicas raising on one epoch lock)
    counts as handled. An aggregate nobody consumes delegates nothing:
    its members' errors stay on the unhandled ledger."""
    futures = list(futures)
    out = Future()
    out._members = futures
    remaining = [len(futures)]
    if not futures:
        out._set([])
        return out

    def on_done(f: Future) -> None:
        if f.is_error and out._consumed:
            f._error_observed = True  # late arrival, aggregate consumed
        if out.is_ready:
            return
        if f.is_error:
            out._set_error(f._error)
            return
        remaining[0] -= 1
        if remaining[0] == 0:
            out._set([x.get() for x in futures])

    for f in futures:
        f.add_done_callback(on_done)
    return out


def any_of(futures: Iterable[Future]) -> Future:
    """choose/when: resolves with (index, value) of the first ready
    future. Same delegation contract as all_of: consuming the aggregate
    handles the losers' errors (racing IS the error policy); a dropped
    aggregate handles nothing."""
    futures = list(futures)
    out = Future()
    out._members = futures

    def make_cb(i: int):
        def cb(f: Future) -> None:
            if f.is_error and out._consumed:
                f._error_observed = True  # loser after a consumed race
            if out.is_ready:
                return
            if f.is_error:
                out._set_error(f._error)
            else:
                out._set((i, f.get()))

        return cb

    for i, f in enumerate(futures):
        f.add_done_callback(make_cb(i))
    return out
