"""The Resolver role: the host state machine around the TPU conflict kernel.

Behavioral mirror of `fdbserver/Resolver.actor.cpp:219-540` (resolveBatch)
and its surrounding actor (`resolverCore` :707): everything the reference
does around `ConflictBatch` — version chaining, duplicate-request replay,
per-proxy state-transaction delivery, MVCC-window GC, metrics — happens
here, while the conflict math itself is one jitted call into
`models.conflict_set.TpuConflictSet`.

Key behaviors reproduced:

* **Version chain.** Requests carry (prev_version, version); a request
  waits `version.when_at_least(prev_version)` and only the request whose
  prev_version equals the current version runs the compute phase — others
  are duplicates (Resolver.actor.cpp:271-307, 525).
* **Duplicate replay.** Replies are retained per proxy in
  `outstanding_batches` until the proxy acks them via
  last_received_version; a duplicate request is answered from the cache,
  and an unknown version gets no answer at all ("Never") — :319-321,
  :517-530.
* **State transactions.** Metadata ("state") transactions committed by any
  proxy's batch must reach every other proxy in version order: each reply
  carries the state transactions of versions in [first_unseen_version,
  req.version) (RecentStateTransactionsInfo :59-123, applied :386-431),
  trimmed once every proxy has seen them (oldest_proxy_version sweep
  :449-474).
* **Memory backpressure.** total_state_bytes over the limit delays new
  batches until old state is trimmed (:254-268, knob
  RESOLVER_STATE_MEMORY_LIMIT).
* **Metrics.** The reference's counters (Resolver.actor.cpp:156-213) and
  latency samples (resolver/queueWait/compute distributions) with the same
  names, for the BASELINE p99 comparison.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.runtime.flow import Notified, Scheduler, Trigger, any_of
from foundationdb_tpu.utils.metrics import CounterCollection, LatencySample
from foundationdb_tpu.utils import commit_debug as _cd
from foundationdb_tpu.utils import trace
from foundationdb_tpu.utils.probes import code_probe, declare

declare(
    "resolver.duplicate_batch_replayed",
    "resolver.unknown_duplicate_never",
    "resolver.too_old",
    "resolver.backpressure_breached",
    "resolver.state_txn_forwarded",
    "resolver.first_unseen_is_current",
)

#: ServerKnobs.RESOLVER_STATE_MEMORY_LIMIT (fdbclient/ServerKnobs.cpp).
DEFAULT_STATE_MEMORY_LIMIT = 1_000_000

#: key-sample capacity before decay (VERDICT r1 weakness 7)
KEY_SAMPLE_LIMIT = 4096


@dataclasses.dataclass
class StateTransaction:
    """StateTransactionRef (fdbclient/CommitTransaction.h): one metadata
    txn forwarded through resolver replies."""

    committed: bool
    mutations: list[Any]


class _ProxyRequestsInfo:
    """Per-proxy bookkeeping (Resolver.actor.cpp ProxyRequestsInfo)."""

    __slots__ = ("last_version", "outstanding_batches")

    def __init__(self):
        self.last_version: int = -1
        self.outstanding_batches: dict[int, ResolveTransactionBatchReply] = {}


class _RecentStateTransactionsInfo:
    """Version -> state txns retained until all proxies have seen them
    (Resolver.actor.cpp:59-123)."""

    def __init__(self):
        self._by_version: dict[int, list[StateTransaction]] = {}
        self._sizes: list[tuple[int, int]] = []  # (version, bytes), ascending

    def add(self, version: int, txns: list[StateTransaction], nbytes: int) -> None:
        self._by_version[version] = txns
        if nbytes > 0:
            self._sizes.append((version, nbytes))

    def erase_up_to(self, oldest_version: int) -> int:
        for v in [v for v in self._by_version if v <= oldest_version]:
            del self._by_version[v]
        erased = 0
        while self._sizes and self._sizes[0][0] <= oldest_version:
            erased += self._sizes.pop(0)[1]
        return erased

    def apply_to_reply(
        self, reply: ResolveTransactionBatchReply, first_unseen: int, commit_version: int
    ) -> None:
        # Prior versions only: the requesting proxy has this version's state
        # txns already; other proxies will see them as a prior version. One
        # inner list per version — the wire format's nested VectorRef shape
        # (ResolverInterface.h:141) — so the proxy applies version by version.
        for v in sorted(self._by_version):
            if first_unseen <= v < commit_version:
                reply.state_mutations.append(self._by_version[v])

    @property
    def size(self) -> int:
        return len(self._sizes)

    def first_version(self) -> int:
        return self._sizes[0][0] if self._sizes else -1


class Resolver:
    """One resolver role instance (Resolver.actor.cpp:126-213 state)."""

    def __init__(
        self,
        sched: Scheduler,
        config: KernelConfig,
        *,
        resolver_id: int = 0,
        resolver_count: int = 1,
        commit_proxy_count: int = 1,
        state_memory_limit: int = None,  # None -> the server knob
        init_version: int = -1,  # reference: Resolver() : version(-1)
        backend: str = None,  # resolver_backend knob: "tpu" | "cpu"
        num_logs: int = 1,  # tlog count for the version-vector tpcv path
    ):
        from foundationdb_tpu.models.conflict_set import make_conflict_set
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        self.sched = sched
        self.resolver_id = resolver_id
        self.resolver_count = resolver_count
        self.commit_proxy_count = commit_proxy_count
        self.state_memory_limit = (
            SERVER_KNOBS.RESOLVER_STATE_MEMORY_LIMIT
            if state_memory_limit is None
            else state_memory_limit
        )

        # Contention-profile routing (VERDICT r4 task 2): with the
        # "tpu" knob the backend is chosen LAZILY at the first batch —
        # hot-key and range-heavy streams measured 0.68x/0.28x AGAINST
        # the device (bench configs 2-3, r5 logs), so their first-batch
        # profile routes them to the CPU skiplist instead. The choice is
        # one-shot: switching backends later would discard the MVCC
        # history; profile DRIFT after the choice raises a TraceEvent
        # (SevWarn) advising reconfiguration, never a silent switch.
        self._config = config
        self._backend_requested = backend
        self._profile: str | None = None
        if (backend or SERVER_KNOBS.RESOLVER_BACKEND) == "tpu":
            self.conflict_set = None  # routed at first resolve
        else:
            self.conflict_set = make_conflict_set(config, backend)
        # kernel-panel fallback (the wire ResolverRole owns the same
        # shape): an unrouted or metrics-less conflict set still
        # reports a zeroed qos.kernel block — REQUIRED_SENSORS pins it
        from foundationdb_tpu.models.conflict_set import KernelStageMetrics

        self._fallback_kernel_metrics = KernelStageMetrics()
        self.version = Notified(init_version)
        self.needed_version = Notified(-(2**62))
        self.check_needed_version = Trigger()
        # Fired whenever needed_version or total_state_bytes changes — the
        # events the reference's backpressure loop waits on
        # (`totalStateBytes.onChange() || neededVersion.onChange()`, :261).
        self._state_changed = Trigger()
        self.total_state_bytes = 0
        self.recent_state = _RecentStateTransactionsInfo()
        self.proxy_info: dict[Optional[str], _ProxyRequestsInfo] = {}
        # Version-vector state (knob ENABLE_VERSION_VECTOR_TLOG_UNICAST;
        # Resolver.actor.cpp:746-750 tpcvVector): per-tlog previous
        # commit version, lazily initialized to the first batch's
        # prev_version (the :486-488 invalidVersion fill).
        self.num_logs = num_logs
        self.tpcv_vector: Optional[list[int]] = None
        # Knob-gated private-mutations path (Resolver.actor.cpp:372-441 +
        # design/transaction-state-store.md): when on, this resolver
        # materializes committed state-txn mutations into its own
        # txnStateStore at resolve time and returns them as
        # reply.private_mutations, so proxies consume resolver-generated
        # metadata instead of re-deriving it.
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        self.private_mutations_enabled = bool(
            SERVER_KNOBS.PROXY_USE_RESOLVER_PRIVATE_MUTATIONS
        )
        self.txn_state_store: dict[bytes, bytes] = {}

        self.counters = CounterCollection(
            "ResolverMetrics",
            [
                "resolveBatchIn",
                "resolveBatchStart",
                "resolveBatchOut",
                "resolvedTransactions",
                "resolvedBytes",
                "resolvedReadConflictRanges",
                "resolvedWriteConflictRanges",
                "transactionsAccepted",
                "transactionsTooOld",
                "transactionsConflicted",
                "resolvedStateTransactions",
                "resolvedStateMutations",
                "resolvedStateBytes",
            ],
        )
        self.resolver_latency = LatencySample("resolverLatency")
        self.queue_wait_latency = LatencySample("queueWaitLatency")
        self.compute_time = LatencySample("computeTime")
        self.queue_depth = LatencySample("queueDepth")
        # busy-fraction smoother (the Ratekeeper's resolver-occupancy
        # input): compute seconds as a decayed rate on the VIRTUAL
        # clock — deterministic per seed, ~0 in sim unless a scenario
        # models compute delay, ~1.0 on a saturated wire resolver
        from foundationdb_tpu.utils.metrics import Smoother

        self.occupancy = Smoother(2.0, clock=sched.now)
        #: virtual per-transaction resolution cost (seconds of VIRTUAL
        #: clock awaited per transaction before the conflict check).
        #: 0.0 in ordinary sims (resolution is instantaneous in virtual
        #: time, so a sim cluster has no finite capacity to saturate);
        #: saturation/overload scenarios set it so offered load past
        #: 1/cost txn/s genuinely backs up — the occupancy Smoother
        #: then reads a true busy fraction, which is the Ratekeeper's
        #: resolver_busy input.
        self.sim_compute_cost_per_txn = 0.0
        # iops sample feeding the ResolutionBalancer (Resolver.actor.cpp:
        # 337-344). Bounded: the reference samples with decay; an
        # unbounded dict leaks on long multi-resolver soaks (VERDICT r1
        # weakness 7).
        self._key_sample: dict[bytes, int] = {}

    def _set_needed_version(self, v: int) -> None:
        if v > self.needed_version.get():
            self.needed_version.set(v)
            self._state_changed.trigger()

    # -- the resolve endpoint --------------------------------------------

    def _route_backend(self, transactions) -> None:
        from foundationdb_tpu.models.conflict_set import (
            backend_for_profile,
            make_conflict_set,
            profile_transactions,
        )
        from foundationdb_tpu.utils.trace import TraceEvent

        self._profile = profile_transactions(transactions)
        # config-aware: with the tiered+dedup kernel configured the
        # hot_key profile routes to the device too (the r6 narrowed
        # router — see backend_for_profile)
        chosen = backend_for_profile(self._profile, self._config)
        self.conflict_set = make_conflict_set(
            self._config, chosen if chosen == "cpu" else "tpu"
        )
        TraceEvent("ResolverBackendRouted").detail(
            "Profile", self._profile
        ).detail("Backend", type(self.conflict_set).__name__).log()

    async def resolve(
        self, req: ResolveTransactionBatchRequest
    ) -> Optional[ResolveTransactionBatchReply]:
        """Handle one ResolveTransactionBatchRequest.

        Returns the reply, or None for the reference's `Never()` (an
        unknown duplicate whose reply was already acked — the proxy will
        retry elsewhere or die).
        """
        request_time = self.sched.now()
        from foundationdb_tpu.utils.spans import Span, SpanContext

        span = Span(
            f"resolver{self.resolver_id}.resolveBatch",
            parent=SpanContext(*req.span) if req.span else None,
            clock=self.sched.now,
        ).attribute("version", req.version)
        try:
            return await self._resolve_spanned(req, span, request_time)
        finally:
            span.finish()  # failure/cancellation paths still export

    async def _resolve_spanned(self, req, span, request_time):
        proxy_key = req.proxy_id if req.prev_version >= 0 else None
        proxy_info = self.proxy_info.setdefault(proxy_key, _ProxyRequestsInfo())
        self.counters.add("resolveBatchIn")
        # Same micro-event locations as the reference, for commit-path
        # latency debugging (Resolver.actor.cpp:244,266,320,509); the
        # strings live in utils/commit_debug.py — the reconstructor and
        # this emitter must never drift.
        if req.debug_id is not None:
            trace.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cd.RESOLVER_BEFORE
            )

        # Memory backpressure (Resolver.actor.cpp:254-268): wait for
        # needed_version / total_state_bytes to move.
        code_probe(
            self.total_state_bytes > self.state_memory_limit,
            "resolver.backpressure_breached",
        )
        while (
            self.total_state_bytes > self.state_memory_limit
            and self.recent_state.size
            and proxy_info.last_version > self.recent_state.first_version()
            and req.version > self.needed_version.get()
        ):
            await self._state_changed.on_trigger()
        if req.debug_id is not None:
            trace.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cd.RESOLVER_AFTER_QUEUE
            )

        # Version chain (:271-293). The loop re-evaluates needed_version on
        # every check_needed_version trigger (the reference's choose/when),
        # so a stalled chain can be broken by raising needed_version.
        while True:
            if (
                self.recent_state.size
                and proxy_info.last_version <= self.recent_state.first_version()
            ):
                self._set_needed_version(
                    max(self.needed_version.get(), req.prev_version)
                )
            waiters = self.version.num_waiting()
            if self.version.get() < req.prev_version:
                waiters += 1
            self.queue_depth.sample(waiters)
            idx, _ = await any_of(
                [
                    self.version.when_at_least(req.prev_version),
                    self.check_needed_version.on_trigger(),
                ]
            )
            if idx == 0:
                self.queue_depth.sample(self.version.num_waiting())
                break
        self.queue_wait_latency.sample(self.sched.now() - request_time)
        if req.debug_id is not None:
            trace.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cd.RESOLVER_AFTER_ORDERER
            )

        if (
            self.sim_compute_cost_per_txn
            and req.transactions
            # a redelivered duplicate (version already advanced past
            # this batch's prev) takes the cached-reply path below and
            # must not re-pay the service delay or re-count busy time
            and self.version.get() == req.prev_version
        ):
            # virtual service time (saturation scenarios): awaited
            # BEFORE the version check below so the duplicate-batch
            # dispatch decision still happens after the last await —
            # the compute phase proper must stay await-free. Successor
            # batches stay blocked on the version chain throughout, so
            # service is serialized and capacity is 1/cost txn/s.
            cost = self.sim_compute_cost_per_txn * len(req.transactions)
            await self.sched.delay(cost)
            # the modeled compute seconds feed the busy-fraction
            # smoother exactly like measured compute in dt_compute
            self.occupancy.add_delta(cost)

        if self.version.get() == req.prev_version:
            # ---- compute phase (no awaits until version.set) -----------
            begin_compute = self.sched.now()
            self.counters.add("resolveBatchStart")
            self.counters.add("resolvedTransactions", len(req.transactions))
            self.counters.add(
                "resolvedBytes", sum(_txn_bytes(tr) for tr in req.transactions)
            )

            if proxy_info.last_version > 0:
                for v in [
                    v
                    for v in proxy_info.outstanding_batches
                    if v <= req.last_received_version
                ]:
                    del proxy_info.outstanding_batches[v]

            first_unseen_version = proxy_info.last_version + 1
            proxy_info.last_version = req.version

            reply = ResolveTransactionBatchReply(debug_id=req.debug_id)
            proxy_info.outstanding_batches[req.version] = reply

            for tr in req.transactions:
                self.counters.add(
                    "resolvedReadConflictRanges", len(tr.read_conflict_ranges)
                )
                self.counters.add(
                    "resolvedWriteConflictRanges", len(tr.write_conflict_ranges)
                )
                # the ResolutionBalancer's key sample, armed ALWAYS
                # (ISSUE 20 — it used to arm only under resolver_count
                # > 1): the future balancer and today's hotspot sensors
                # both need conflict-range density on single-resolver
                # clusters too
                for b, _e in tr.read_conflict_ranges + tr.write_conflict_ranges:
                    self._key_sample[b] = self._key_sample.get(b, 0) + 1
                if len(self._key_sample) > KEY_SAMPLE_LIMIT:
                    self._decay_key_sample()

            if self.conflict_set is None:
                self._route_backend(req.transactions)
            elif self._profile is not None and req.transactions:
                from foundationdb_tpu.models.conflict_set import (
                    profile_transactions,
                )

                drifted = profile_transactions(req.transactions)
                if drifted != self._profile:
                    from foundationdb_tpu.utils.trace import (
                        SEV_WARN,
                        TraceEvent,
                    )

                    TraceEvent(
                        "ResolverContentionDrift", severity=SEV_WARN
                    ).detail("Chosen", self._profile).detail(
                        "Observed", drifted
                    ).log()
                    self._profile = drifted  # warn once per change
            result = self.conflict_set.resolve(req.transactions, req.version)
            reply.committed = result.verdicts
            reply.conflicting_key_range_map = result.conflicting_key_ranges
            n_committed = sum(
                1 for v in result.verdicts if v == TransactionResult.COMMITTED
            )
            n_too_old = sum(
                1 for v in result.verdicts if v == TransactionResult.TOO_OLD
            )
            self.counters.add("transactionsAccepted", n_committed)
            self.counters.add("transactionsTooOld", n_too_old)
            code_probe(n_too_old > 0, "resolver.too_old")
            self.counters.add(
                "transactionsConflicted",
                len(req.transactions) - n_committed - n_too_old,
            )

            # ---- state transactions (:386-431) -------------------------
            assert req.prev_version >= 0 or not req.txn_state_transactions
            state_txns: list[StateTransaction] = []
            state_bytes = 0
            for t in req.txn_state_transactions:
                tr = req.transactions[t]
                committed = reply.committed[t] == TransactionResult.COMMITTED
                state_txns.append(
                    StateTransaction(
                        committed=committed,
                        mutations=list(tr.mutations),
                    )
                )
                if committed and self.private_mutations_enabled:
                    # private-mutations path (:372-441): emit candidate
                    # metadata for the proxy (which filters by the GLOBAL
                    # min-combined verdict) and, in single-resolver
                    # configurations — where the local verdict IS the
                    # global one — materialize into this resolver's
                    # txnStateStore. Multi-resolver stores stay passive:
                    # a resolver cannot know the global verdict at
                    # resolve time (the reference's knob path shares this
                    # limitation; it ships default-off,
                    # ServerKnobs.cpp:549).
                    from foundationdb_tpu.models.types import (
                        is_metadata_mutation,
                    )

                    metas = [
                        m for m in tr.mutations if is_metadata_mutation(m)
                    ]
                    if metas:
                        reply.private_mutations[t] = metas
                        if self.resolver_count == 1:
                            for m in metas:
                                self._apply_state_mutation(m)
                state_bytes += sum(_mutation_bytes(m) for m in tr.mutations)
                self.counters.add("resolvedStateMutations", len(tr.mutations))
            self.counters.add("resolvedStateTransactions", len(req.txn_state_transactions))
            self.counters.add("resolvedStateBytes", state_bytes)
            self.recent_state.add(req.version, state_txns, state_bytes)
            self.recent_state.apply_to_reply(reply, first_unseen_version, req.version)
            code_probe(len(state_txns) > 0, "resolver.state_txn_forwarded")
            code_probe(
                first_unseen_version == req.version,
                "resolver.first_unseen_is_current",
            )

            # ---- trim state every proxy has seen (:449-474) ------------
            # The map holds one entry per proxy plus the master's (key None,
            # created by the recovery request with prev_version < 0); state
            # is only trimmed once every expected peer has reported in.
            assert len(self.proxy_info) <= self.commit_proxy_count + 1
            oldest_proxy_version = req.version
            for key, info in self.proxy_info.items():
                if key is not None:
                    oldest_proxy_version = min(info.last_version, oldest_proxy_version)
            any_popped = False
            if (
                first_unseen_version <= oldest_proxy_version
                and len(self.proxy_info) == self.commit_proxy_count + 1
            ):
                erased = self.recent_state.erase_up_to(oldest_proxy_version)
                any_popped = erased > 0
                state_bytes -= erased

            # ---- version-vector tpcvMap (:475-495, knob-gated) ---------
            from foundationdb_tpu.utils.knobs import SERVER_KNOBS

            if (
                SERVER_KNOBS.ENABLE_VERSION_VECTOR_TLOG_UNICAST
                and self.num_logs
            ):
                # state/metadata batches broadcast to every log; plain
                # batches touch only the written tags' log locations
                # (tag -> log via round-robin, our LogSystem's layout)
                if state_txns or reply.private_mutations:
                    written_tlogs = set(range(self.num_logs))
                else:
                    written_tlogs = {
                        t % self.num_logs for t in req.written_tags
                    }
                # the reference refills while tpcvVector[0] ==
                # invalidVersion (-1): a recovery batch's prev_version
                # of -1 leaves the vector "uninitialized" so the first
                # real batch seeds it with ITS prev_version (:486-488)
                if self.tpcv_vector is None or self.tpcv_vector[0] == -1:
                    self.tpcv_vector = [req.prev_version] * self.num_logs
                for tl in sorted(written_tlogs):
                    reply.tpcv_map[tl] = self.tpcv_vector[tl]
                    self.tpcv_vector[tl] = req.version
                reply.written_tags = frozenset(req.written_tags)

            self.version.set(req.version)
            breached = (
                self.total_state_bytes <= self.state_memory_limit
                < self.total_state_bytes + state_bytes
            )
            self.total_state_bytes += state_bytes
            self._state_changed.trigger()
            if any_popped or breached:
                self.check_needed_version.trigger()
            dt_compute = self.sched.now() - begin_compute
            self.compute_time.sample(dt_compute)
            self.occupancy.add_delta(dt_compute)
        else:
            # duplicate resolve batch request (:513)
            code_probe(
                req.version in proxy_info.outstanding_batches,
                "resolver.duplicate_batch_replayed",
            )

        self.counters.add("resolveBatchOut")
        self.resolver_latency.sample(self.sched.now() - request_time)
        if req.debug_id is not None:
            trace.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cd.RESOLVER_AFTER
            )
        out = proxy_info.outstanding_batches.get(req.version)
        code_probe(out is None, "resolver.unknown_duplicate_never")
        span.attribute("txns", len(req.transactions))
        return out  # None == the reference's Never()

    # -- saturation sensors (the Ratekeeper's resolver occupancy input) ----

    def saturation(self) -> dict:
        """The resolver's qos sensor block: the reference's exact four
        distributions (resolverLatencyDist / queueWaitLatencyDist /
        computeTimeDist / queueDepthDist, Resolver.actor.cpp:156-213)
        plus state-memory pressure and — on kernel backends — the TPU
        occupancy summary from KernelStageMetrics. All virtual-clock
        samples: deterministic per seed, safe next to trace digests."""
        out = {
            "queue_depth": self.version.num_waiting(),
            "occupancy": self.occupancy.smooth_rate(),
            "queue_depth_dist": self.queue_depth.as_dict(),
            "queue_wait_dist": self.queue_wait_latency.as_dict(),
            "compute_time_dist": self.compute_time.as_dict(),
            "resolver_latency_dist": self.resolver_latency.as_dict(),
            "state_bytes": self.total_state_bytes,
            "state_memory_limit": self.state_memory_limit,
            "state_pressure": (
                self.total_state_bytes / self.state_memory_limit
                if self.state_memory_limit else 0.0
            ),
            # the conflict-range key sample (ISSUE 20): the future
            # ResolutionBalancer's split input, surfaced as a sensor —
            # top conflict-range begin keys by touch count
            "key_sample": self._key_sample_qos(),
        }
        # kernel panel: ALWAYS present so fdbtop/REQUIRED_SENSORS can
        # pin it — an unrouted or metrics-less backend reports the
        # zeroed fallback (which still carries the process-global
        # compile-cache counters), never a missing key
        metrics = (
            getattr(self.conflict_set, "metrics", None)
            or self._fallback_kernel_metrics
        )
        out["kernel"] = metrics.qos()
        return out

    # -- balancer endpoints (ResolverInterface metrics/split) -------------

    def _apply_state_mutation(self, m) -> None:
        """Materialize one metadata mutation into the resolver-side
        txnStateStore (the LogSystemDiskQueueAdapter-materialized store,
        design/transaction-state-store.md)."""
        from foundationdb_tpu.models.types import apply_state_mutation

        apply_state_mutation(self.txn_state_store, m)

    def _key_sample_qos(self) -> dict:
        """The key-sample sensor block (sampling.key_sample_qos so the
        sim and wire resolvers can never report divergent shapes)."""
        from foundationdb_tpu.cluster.sampling import key_sample_qos

        return key_sample_qos(self._key_sample)

    def _decay_key_sample(self) -> None:
        """Halve all counts, dropping zeros; if the key set itself is too
        wide, keep the heaviest half. Split points stay representative
        (hot boundaries survive decay by construction) while memory stays
        O(KEY_SAMPLE_LIMIT) forever."""
        self._key_sample = {
            k: c // 2 for k, c in self._key_sample.items() if c // 2 > 0
        }
        if len(self._key_sample) > KEY_SAMPLE_LIMIT:
            top = sorted(self._key_sample.items(), key=lambda kv: -kv[1])
            self._key_sample = dict(top[: KEY_SAMPLE_LIMIT // 2])

    def metrics(self) -> int:
        """ResolutionMetricsRequest: total sampled conflict-range ops."""
        return sum(self._key_sample.values())

    def split_point(self, begin: bytes, end: bytes, offset_fraction: float) -> bytes:
        """ResolutionSplitRequest: a key splitting the sampled load in
        [begin, end) at the given fraction (ResolutionBalancer semantics)."""
        keys = sorted(k for k in self._key_sample if begin <= k < end)
        if not keys:
            return begin
        total = sum(self._key_sample[k] for k in keys)
        target = total * offset_fraction
        acc = 0
        for k in keys:
            acc += self._key_sample[k]
            if acc >= target:
                return k
        return keys[-1]


def _mutation_bytes(m: Any) -> int:
    try:
        return len(m[1]) + len(m[2]) + 8  # (type, param1, param2)
    except Exception:
        return 32


def _txn_bytes(tr: CommitTransaction) -> int:
    """CommitTransactionRef::expectedSize analog (conflict ranges + mutations)."""
    n = sum(
        len(b) + len(e)
        for b, e in tr.read_conflict_ranges + tr.write_conflict_ranges
    )
    return n + sum(_mutation_bytes(m) for m in tr.mutations)
