"""Per-`async def` control-flow graphs: flowcheck's dataflow substrate.

The actor compiler's oldest lesson — *all state may change across a
`wait()`* — is invisible to purely syntactic rules: whether a read is
stale depends on what happens along the control-flow paths between the
read, the yield point, and the use. This module builds the structure
the `flow.*` rule family (rules_flow.py) needs:

* `iter_async_functions(tree)` walks EVERY `async def` — module-level,
  methods, nested actors inside functions (the soak workload shape),
  decorated actors — none of them may escape the walk.
* `build_cfg(fn, shared)` lowers one async function to a graph of
  basic blocks whose contents are ordered *events*: yield points
  (`await`, `async for` steps, `async with` enter/exit, awaits inside
  comprehensions), reads/writes of shared mutable state, local
  definitions with their shared-read taint, local uses, validation
  guards, and invariant-check calls.
* `SharedModel` decides what counts as *shared mutable state*: `self.X`
  attributes a method outside `__init__` writes, module globals some
  function mutates, and captured mutables — enclosing-function locals
  (the nested-actor closure pattern) that any function in the closure
  mutates in place or rebinds via `nonlocal`.

Precision notes, deliberate:

* Shared-object keys are `(base, sub)` pairs; `sub` is the dump of a
  constant/Name subscript when present, `None` for whole-object access.
  Two keys conflict when bases match and either sub is `None` or both
  are equal — distinct constant subscripts are disjoint on purpose
  (per-key dict slots are independent state).
* An attribute only mutated in `__init__` (wiring, not state) is not
  shared-mutable: staleness across a wait is impossible for it.
* Calls to local helpers are opaque (no interprocedural dataflow); the
  guard/check rules lean on the project's re-validate-after-wait idiom
  instead.
* `finally` bodies are lowered after the try/handler JOIN only: a
  re-validation placed in a finally does not register on return paths.
  Known conservative edge — put re-checks before the return (the
  pattern the whole rule family teaches anyway).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

#: method leaves that mutate their receiver in place
MUTATING_METHODS = {
    "append", "add", "pop", "popitem", "remove", "discard", "clear",
    "update", "extend", "insert", "setdefault", "sort", "reverse",
}
#: method leaves that read their receiver (first arg keys the slot)
READING_METHODS = {"get", "index", "count", "copy"}

#: leaf-name shape of an invariant-check call (guard-not-rechecked)
CHECK_CALL_PREFIXES = ("check", "validate", "verify", "ensure", "assert")


# -- events ----------------------------------------------------------------

AWAIT = "await"      # ("await", node)
READ = "read"        # ("read", key, node[, weak]) — weak = receiver of an
#                      unknown method call (observes the object; not a
#                      value read rules should anchor on)
STMT = "stmt"        # ("stmt",) — statement boundary marker
WRITE = "write"      # ("write", key, frozenset[RHS local names], node)
DEF = "def"          # ("def", name, frozenset[RHS shared keys], node)
USE = "use"          # ("use", name, in_test, node, deref) — deref: the
#                      name is immediately dereferenced (attr/subscript
#                      base): a live read THROUGH the alias, not a use
#                      of a stale snapshotted value
GUARD = "guard"      # ("guard", kind, frozenset[keys], node)
CHECK = "check"      # ("check", calldump, node)
RETURN = "return"    # ("return", node)
RAISE = "raise"      # ("raise", node)
NARROW = "narrow"    # ("narrow", name, "none"|"notnone", node) — branch
#                      fact from an `if x is (not) None` / `if (not) x`
#                      test on a plain local name: the first event of
#                      each branch block, so path walks can kill
#                      branches infeasible for what they track


def _narrow_of(test: ast.expr) -> Optional[tuple[str, str, str]]:
    """(name, true-branch fact, false-branch fact) for branch tests a
    path walk can narrow on; None for anything richer. Truthiness tests
    on a plain name narrow None-ness too — a held resource object is
    truthy (none of the tracked handle types define __bool__)."""
    if isinstance(test, ast.Name):
        return (test.id, "notnone", "none")
    if isinstance(test, ast.UnaryOp) and isinstance(
        test.op, ast.Not
    ) and isinstance(test.operand, ast.Name):
        return (test.operand.id, "none", "notnone")
    if isinstance(test, ast.Compare) and isinstance(
        test.left, ast.Name
    ) and len(test.ops) == 1 and isinstance(
        test.comparators[0], ast.Constant
    ) and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return (test.left.id, "none", "notnone")
        if isinstance(test.ops[0], ast.IsNot):
            return (test.left.id, "notnone", "none")
    return None


def keys_conflict(a: tuple, b: tuple) -> bool:
    """(base, sub) keys address the same state: same base and either
    side is a whole-object access or the subscripts dump equal."""
    return a[0] == b[0] and (a[1] is None or b[1] is None or a[1] == b[1])


class Block:
    """One basic block: an ordered event list plus successor edges."""

    __slots__ = ("events", "succs", "exc_succs", "terminated")

    def __init__(self):
        self.events: list[tuple] = []
        self.succs: list["Block"] = []
        #: edges taken only when an exception diverts control into a
        #: handler — rule path-walks treat these as abandonment (the
        #: guarded action never happens), not as serving-stale paths
        self.exc_succs: list["Block"] = []
        self.terminated = False  # ends in return/raise/break/continue

    def add_succ(self, b: "Block") -> None:
        if b is not None and b not in self.succs:
            self.succs.append(b)

    def add_exc_succ(self, b: "Block") -> None:
        if b is not None and b not in self.exc_succs:
            self.exc_succs.append(b)


# -- function discovery ----------------------------------------------------


def annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._fc_parent = node  # type: ignore[attr-defined]


def _enclosing_chain(node: ast.AST) -> list[ast.AST]:
    chain = []
    cur = getattr(node, "_fc_parent", None)
    while cur is not None:
        chain.append(cur)
        cur = getattr(cur, "_fc_parent", None)
    return chain


@dataclasses.dataclass
class FuncInfo:
    node: ast.AsyncFunctionDef
    qualname: str
    #: nearest enclosing class whose method chain binds `self`, if any
    class_node: Optional[ast.ClassDef]
    self_name: Optional[str]
    #: enclosing function nodes, innermost first (nested-actor closures)
    enclosing: list


def iter_async_functions(tree: ast.Module) -> Iterator[FuncInfo]:
    """Every async def in the module — nested and decorated included.

    This is the blind-spot contract (tests pin it): an actor defined
    inside another function (the soak-workload shape), behind a
    decorator, or inside a class inside a function is still walked.
    """
    yield from iter_functions(tree, sync=False)


def iter_functions(tree: ast.Module, *, sync: bool = True
                   ) -> Iterator[FuncInfo]:
    """Every function def in the module, sync and async alike (the
    resource-ownership pass tracks `open()`/Popen acquires in plain
    defs too). `_Builder` only touches `fn.args`/`fn.body`, so the CFG
    lowering applies unchanged to sync functions — AWAIT events simply
    never occur in them."""
    kinds = (
        (ast.FunctionDef, ast.AsyncFunctionDef) if sync
        else ast.AsyncFunctionDef
    )
    annotate_parents(tree)
    for node in ast.walk(tree):
        if not isinstance(node, kinds):
            continue
        chain = _enclosing_chain(node)
        enclosing = [
            n for n in chain
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        class_node = None
        self_name = None
        # the function that binds `self` is the nearest enclosing
        # function whose direct parent is a ClassDef (a method); `node`
        # itself may be that method
        for fn in [node] + enclosing:
            parent = getattr(fn, "_fc_parent", None)
            if isinstance(parent, ast.ClassDef):
                args = fn.args.posonlyargs + fn.args.args
                if args and args[0].arg in ("self",):
                    class_node = parent
                    self_name = args[0].arg
                break
        parts = [
            n.name for n in reversed([node] + enclosing)
        ]
        yield FuncInfo(
            node=node,
            qualname=".".join(parts),
            class_node=class_node,
            self_name=self_name,
            enclosing=enclosing,
        )


# -- the shared-mutable-state model ----------------------------------------


def _local_bindings(fn) -> set[str]:
    """Names a function binds locally (params + every binding form),
    NOT descending into nested function scopes."""
    out = set()
    a = fn.args
    for arg in (
        a.posonlyargs + a.args + a.kwonlyargs
        + ([a.vararg] if a.vararg else [])
        + ([a.kwarg] if a.kwarg else [])
    ):
        out.add(arg.arg)

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                out.add(child.name)
                continue  # separate scope
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Name) and isinstance(
                child.ctx, (ast.Store, ast.Del)
            ):
                out.add(child.id)
            elif isinstance(child, ast.ExceptHandler) and child.name:
                out.add(child.name)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                for al in child.names:
                    out.add((al.asname or al.name).split(".")[0])
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    return out


def _inplace_mutated_names(root) -> set[str]:
    """Bare names whose object is mutated in place anywhere under
    `root`: subscript stores (`d[k] = v`, `d[k] += v`, `del d[k]`),
    mutating method calls (`d.update(...)`), or `nonlocal` rebinds."""
    out = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            if isinstance(node.value, ast.Name):
                out.add(node.value.id)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in MUTATING_METHODS and isinstance(
                node.func.value, ast.Name
            ):
                out.add(node.func.value.id)
        elif isinstance(node, ast.Nonlocal):
            out.update(node.names)
    return out


def _class_mutable_attrs(cls: ast.ClassDef) -> set[str]:
    """Attributes of `self` some method writes OUTSIDE __init__ —
    the ones whose value can genuinely change across a wait()."""
    out = set()
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name == "__init__":
            continue
        args = fn.args.posonlyargs + fn.args.args
        if not args or args[0].arg != "self":
            continue
        self_name = args[0].arg
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ) and node.value.id == self_name:
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    out.add(node.attr)
                else:
                    parent = getattr(node, "_fc_parent", None)
                    if isinstance(parent, ast.Subscript) and isinstance(
                        parent.ctx, (ast.Store, ast.Del)
                    ) and parent.value is node:
                        out.add(node.attr)
                    elif isinstance(parent, ast.Attribute) and (
                        parent.value is node
                    ) and parent.attr in MUTATING_METHODS:
                        grand = getattr(parent, "_fc_parent", None)
                        if isinstance(grand, ast.Call) and (
                            grand.func is parent
                        ):
                            out.add(node.attr)
    return out


def _memo(node: ast.AST, attr: str, compute):
    """Per-AST-node memo: module/class/function facts are independent
    of WHICH async def is being analyzed, so one SharedModel per async
    def must not recompute them (quadratic on files with many actors —
    check.sh prints the gate's wall time to keep this honest)."""
    cached = getattr(node, attr, None)
    if cached is None:
        cached = compute(node)
        setattr(node, attr, cached)
    return cached


def _module_globals_mut(tree: ast.Module) -> set[str]:
    """Module-level names some function mutates in place or rebinds
    via `global` — computed once per module."""
    out: set[str] = set()
    module_names = {
        t.id
        for stmt in tree.body
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign))
        for t in ast.walk(stmt)
        if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store)
    }
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    out.update(sub.names)
            out |= _memo(
                node, "_fc_inplace", _inplace_mutated_names
            ) & module_names
    return out


class SharedModel:
    """Answers "is this expression a read/write of shared mutable
    state, and under which key?" for one function's analysis."""

    def __init__(self, tree: ast.Module, info: FuncInfo):
        self.info = info
        self.self_name = info.self_name
        self.mutable_attrs = (
            _memo(info.class_node, "_fc_mutable_attrs", _class_mutable_attrs)
            if info.class_node is not None else set()
        )
        own = _memo(info.node, "_fc_bindings", _local_bindings)
        # captured mutables: bound in an enclosing function's scope,
        # mutated in place somewhere under the OUTERMOST enclosing
        # function (any sibling actor counts — that's the race)
        self.captured: set[str] = set()
        if info.enclosing:
            outermost = info.enclosing[-1]
            mutated = _memo(
                outermost, "_fc_inplace", _inplace_mutated_names
            )
            bound_up = set()
            for fn in info.enclosing:
                bound_up |= _memo(fn, "_fc_bindings", _local_bindings)
            self.captured = (bound_up - own) & mutated
        # module globals some function mutates — shadowed locals aside
        self.globals_mut = _memo(
            tree, "_fc_globals_mut", _module_globals_mut
        ) - own

    # -- key resolution ---------------------------------------------------

    def base_key(self, node: ast.expr) -> Optional[str]:
        """The shared base a bare expression addresses, if any:
        `self.X` (mutable attr) or a captured/global mutable name."""
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            if (
                node.value.id == self.self_name
                and node.attr in self.mutable_attrs
            ):
                return f"{self.self_name}.{node.attr}"
            return None
        if isinstance(node, ast.Name):
            if node.id in self.captured or node.id in self.globals_mut:
                return node.id
        return None

    @staticmethod
    def sub_key(slice_node: ast.expr) -> Optional[str]:
        """Subscript identity when statically comparable: constants and
        bare names dump stably; anything else is whole-object (None)."""
        if isinstance(slice_node, (ast.Constant, ast.Name)):
            return ast.dump(slice_node)
        return None


# -- CFG construction ------------------------------------------------------


class _Builder:
    def __init__(self, fn: ast.AsyncFunctionDef, shared: SharedModel):
        self.fn = fn
        self.shared = shared
        self.blocks: list[Block] = []
        self.params = {
            a.arg
            for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            )
        }

    def new_block(self) -> Block:
        b = Block()
        self.blocks.append(b)
        return b

    # -- expression lowering (evaluation order preserved) -----------------

    def expr(self, node, out: list[tuple], in_test: bool = False) -> None:
        if node is None:
            return
        sh = self.shared
        if isinstance(node, ast.Await):
            self.expr(node.value, out, in_test)
            out.append((AWAIT, node))
            return
        if isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            base = sh.base_key(node)
            if base is not None:
                out.append((READ, (base, None), node))
                return
            self.expr(node.value, out, in_test)
            return
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            base = sh.base_key(node.value)
            self.expr(node.slice, out, in_test)
            if base is not None:
                out.append((READ, (base, sh.sub_key(node.slice)), node))
            else:
                self.expr(node.value, out, in_test)
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            base = sh.base_key(node)
            if base is not None:
                out.append((READ, (base, None), node))
            else:
                parent = getattr(node, "_fc_parent", None)
                deref = (
                    isinstance(parent, (ast.Attribute, ast.Subscript))
                    and parent.value is node
                ) or (
                    isinstance(parent, ast.Call) and parent.func is node
                )
                out.append((USE, node.id, in_test, node, deref))
            return
        if isinstance(node, ast.Call):
            # receiver-method reads/writes on shared bases
            if isinstance(node.func, ast.Attribute):
                base = sh.base_key(node.func.value)
                if base is not None:
                    arg0 = node.args[0] if node.args else None
                    sub = sh.sub_key(arg0) if arg0 is not None else None
                    for a in node.args:
                        self.expr(a, out, in_test)
                    for k in node.keywords:
                        self.expr(k.value, out, in_test)
                    leaf = node.func.attr
                    if leaf in READING_METHODS:
                        out.append((READ, (base, sub), node))
                    elif leaf == "setdefault":
                        out.append((READ, (base, sub), node))
                        out.append((WRITE, (base, sub), frozenset(), node))
                    elif leaf in MUTATING_METHODS:
                        out.append((WRITE, (base, None), frozenset(), node))
                    else:
                        # unknown method: conservatively a WEAK read
                        # (it observes the object — enough to count as
                        # a refresh — but not a value anchor for the
                        # stale/rmw rules)
                        out.append((READ, (base, None), node, True))
                    return
            self.expr(node.func, out, in_test)
            for a in node.args:
                self.expr(a, out, in_test)
            for k in node.keywords:
                self.expr(k.value, out, in_test)
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate (later) execution scope
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # comprehensions execute inline in the enclosing async
            # function — an `await` inside one IS a yield point here
            # (the classic walker blind spot; tests pin it)
            for gen in node.generators:
                self.expr(gen.iter, out, in_test)
                if getattr(gen, "is_async", False):
                    out.append((AWAIT, node))
                for if_ in gen.ifs:
                    self.expr(if_, out, in_test)
            if isinstance(node, ast.DictComp):
                self.expr(node.key, out, in_test)
                self.expr(node.value, out, in_test)
            else:
                self.expr(node.elt, out, in_test)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.expr(child, out, in_test)
            elif isinstance(child, ast.keyword):
                self.expr(child.value, out, in_test)
            elif isinstance(child, ast.comprehension):
                self.expr(child.iter, out, in_test)

    def _store_target(self, target, value_events, value_node, out) -> None:
        sh = self.shared
        if any(ev[0] == AWAIT for ev in value_events):
            # the value was produced AT a yield point (await in the
            # RHS): it is fresh as of that await, not a pre-wait
            # snapshot — argument taint through an awaited call is not
            # a live-state read
            rhs_shared = frozenset()
        else:
            rhs_shared = frozenset(
                ev[1] for ev in value_events
                if ev[0] == READ and not (len(ev) > 3 and ev[3])
            )
        rhs_locals = frozenset(
            ev[1] for ev in value_events if ev[0] == USE
        )
        node = value_node if value_node is not None else target
        if isinstance(target, ast.Name):
            base = sh.base_key(target)
            if base is not None:
                out.append((WRITE, (base, None), rhs_locals, node))
            else:
                out.append((DEF, target.id, rhs_shared, node))
        elif isinstance(target, ast.Attribute):
            base = sh.base_key(
                ast.Attribute(
                    value=target.value, attr=target.attr, ctx=ast.Load()
                )
            ) if isinstance(target.value, ast.Name) else None
            if base is not None:
                out.append((WRITE, (base, None), rhs_locals, node))
            else:
                self.expr(target.value, out)
        elif isinstance(target, ast.Subscript):
            base = sh.base_key(target.value)
            self.expr(target.slice, out)
            if base is not None:
                out.append(
                    (WRITE, (base, sh.sub_key(target.slice)), rhs_locals,
                     node)
                )
            else:
                self.expr(target.value, out)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._store_target(el, value_events, value_node, out)
        elif isinstance(target, ast.Starred):
            self._store_target(target.value, value_events, value_node, out)

    # -- guards -----------------------------------------------------------

    def _body_raises(self, body: list[ast.stmt]) -> bool:
        """The body of a validation guard: ends in `raise`, diverts
        nowhere else (a log line before the raise is still a guard)."""
        if not body or not isinstance(body[-1], ast.Raise):
            return False
        for s in body:
            for sub in ast.walk(s):
                if isinstance(sub, (ast.Await, ast.Return)):
                    return False
        return True

    def _guard_event(self, test, kind: str, node) -> Optional[tuple]:
        """A validation guard: the test reads shared mutable state AND
        some request-derived operand (a parameter or plain local) — the
        `version < self.oldest_version` shape. Pure liveness flags
        (`if self._stopped: raise`) are excluded: they carry no request
        value whose validation could go stale in the same way."""
        ev: list[tuple] = []
        self.expr(test, ev, in_test=True)
        keys = frozenset(e[1] for e in ev if e[0] == READ)
        if not keys:
            return None
        if not any(e[0] == USE for e in ev):
            return None
        return (GUARD, kind, keys, node)

    def _check_call_event(self, call: ast.Call, node) -> Optional[tuple]:
        leaf = None
        if isinstance(call.func, ast.Attribute):
            leaf = call.func.attr
        elif isinstance(call.func, ast.Name):
            leaf = call.func.id
        if leaf is None or not call.args:
            return None
        stem = leaf.lstrip("_")
        if not stem.startswith(CHECK_CALL_PREFIXES):
            return None
        # at least one argument must be a parameter of THIS function:
        # the request value whose validation the wait can invalidate
        if not any(
            isinstance(a, ast.Name) and a.id in self.params
            for a in call.args
        ):
            return None
        return (CHECK, ast.dump(call), node)

    # -- statement lowering -----------------------------------------------

    def build(self) -> Block:
        entry = self.new_block()
        exit_block = self.stmts(self.fn.body, entry, [])
        return entry

    def stmts(self, body, cur: Block,
              loops: list[tuple[Block, Block]]) -> Optional[Block]:
        """Lower a statement list starting in `cur`; returns the block
        control falls out of (None if every path terminated)."""
        for stmt in body:
            if cur is None:
                cur = self.new_block()  # unreachable tail: keep honest
            cur = self.stmt(stmt, cur, loops)
        return cur

    def _lower_loop_else(self, stmt, header: Block, after: Block,
                         loops, exits: bool) -> None:
        """Loop exits: the else clause runs on EXHAUSTION only — break
        jumps straight to `after`, skipping it (lowering the else into
        `after` would run it on break paths and hide stale reads the
        break path never refreshes). `exits` = the loop can exhaust
        (False for `while True:`)."""
        if stmt.orelse:
            if not exits:
                return  # while True ... else: unreachable
            else_b = self.new_block()
            header.add_succ(else_b)
            else_out = self.stmts(stmt.orelse, else_b, loops)
            if else_out is not None:
                else_out.add_succ(after)
        elif exits:
            header.add_succ(after)

    def stmt(self, stmt, cur: Block, loops) -> Optional[Block]:
        ev = cur.events
        ev.append((STMT,))
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            ve: list[tuple] = []
            self.expr(value, ve)
            ev.extend(ve)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                self._store_target(t, ve, value, ev)
            return cur
        if isinstance(stmt, ast.AugAssign):
            # load of the target first (the R of the RMW)...
            loadish: list[tuple] = []
            t = stmt.target
            base = None
            if isinstance(t, ast.Name):
                base = self.shared.base_key(t)
                if base is not None:
                    loadish.append((READ, (base, None), t))
                else:
                    loadish.append((USE, t.id, False, t, False))
            elif isinstance(t, ast.Attribute) and isinstance(
                t.value, ast.Name
            ):
                probe = ast.Attribute(value=t.value, attr=t.attr,
                                      ctx=ast.Load())
                base = self.shared.base_key(probe)
                if base is not None:
                    loadish.append((READ, (base, None), t))
            elif isinstance(t, ast.Subscript):
                base = self.shared.base_key(t.value)
                self.expr(t.slice, loadish)
                if base is not None:
                    loadish.append(
                        (READ, (base, self.shared.sub_key(t.slice)), t)
                    )
            ev.extend(loadish)
            ve: list[tuple] = []
            self.expr(stmt.value, ve)
            ev.extend(ve)
            # ...then the store
            self._store_target(stmt.target, loadish + ve, stmt.value, ev)
            return cur
        if isinstance(stmt, ast.Expr):
            ve: list[tuple] = []
            self.expr(stmt.value, ve)
            ev.extend(ve)
            if isinstance(stmt.value, ast.Call):
                ce = self._check_call_event(stmt.value, stmt)
                if ce is not None:
                    ev.append(ce)
            return cur
        if isinstance(stmt, ast.Return):
            ve: list[tuple] = []
            self.expr(stmt.value, ve)
            ev.extend(ve)
            ev.append((RETURN, stmt))
            cur.terminated = True
            return None
        if isinstance(stmt, ast.Raise):
            self.expr(stmt.exc, ev)
            self.expr(stmt.cause, ev)
            ev.append((RAISE, stmt))
            cur.terminated = True
            return None
        if isinstance(stmt, ast.If):
            te: list[tuple] = []
            self.expr(stmt.test, te, in_test=True)
            ev.extend(te)
            if self._body_raises(stmt.body) and not stmt.orelse:
                ge = self._guard_event(stmt.test, "if", stmt)
                if ge is not None:
                    ev.append(ge)
            nar = _narrow_of(stmt.test)
            body_b = self.new_block()
            if nar is not None:
                body_b.events.append((NARROW, nar[0], nar[1], stmt.test))
            cur.add_succ(body_b)
            body_out = self.stmts(stmt.body, body_b, loops)
            if stmt.orelse:
                else_b = self.new_block()
                if nar is not None:
                    else_b.events.append(
                        (NARROW, nar[0], nar[2], stmt.test)
                    )
                cur.add_succ(else_b)
                else_out = self.stmts(stmt.orelse, else_b, loops)
            elif nar is not None:
                # the fall-through IS the false branch: give it its own
                # block so the narrowing fact rides the right edge
                else_b = self.new_block()
                else_b.events.append((NARROW, nar[0], nar[2], stmt.test))
                cur.add_succ(else_b)
                else_out = else_b
            else:
                else_out = cur
            join = self.new_block()
            fell = False
            for out in (body_out, else_out):
                if out is not None:
                    out.add_succ(join)
                    fell = True
            return join if fell else None
        if isinstance(stmt, (ast.While,)):
            header = self.new_block()
            cur.add_succ(header)
            self.expr(stmt.test, header.events, in_test=True)
            after = self.new_block()
            body_b = self.new_block()
            header.add_succ(body_b)
            # `while True:` never falls out — its only exits are
            # break/return/raise; a synthetic exit edge would
            # manufacture stale paths that cannot execute
            exits = not (
                isinstance(stmt.test, ast.Constant) and stmt.test.value
            )
            body_out = self.stmts(stmt.body, body_b, loops + [(header, after)])
            if body_out is not None:
                body_out.add_succ(header)
            self._lower_loop_else(stmt, header, after, loops, exits)
            return after
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.expr(stmt.iter, ev)
            header = self.new_block()
            cur.add_succ(header)
            if isinstance(stmt, ast.AsyncFor):
                header.events.append((AWAIT, stmt))  # each step yields
            # the loop target binds fresh each iteration
            self._store_target(stmt.target, [], stmt.iter, header.events)
            after = self.new_block()
            body_b = self.new_block()
            header.add_succ(body_b)
            body_out = self.stmts(stmt.body, body_b, loops + [(header, after)])
            if body_out is not None:
                body_out.add_succ(header)
            self._lower_loop_else(stmt, header, after, loops, True)
            return after
        if isinstance(stmt, ast.Break):
            cur.terminated = True
            if loops:
                cur.add_succ(loops[-1][1])
            return None
        if isinstance(stmt, ast.Continue):
            cur.terminated = True
            if loops:
                cur.add_succ(loops[-1][0])
            return None
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            # the try body gets a FRESH block: statements lowered into
            # `cur` before the try are outside the protected region and
            # must not grow exception edges into the handlers (a stale
            # pre-try state escaping into a handler manufactures paths
            # that cannot execute — see rules_res' loop-carried case)
            before = len(self.blocks)
            body_b = self.new_block()
            cur.add_succ(body_b)
            body_out = self.stmts(stmt.body, body_b, loops)
            body_blocks = self.blocks[before:]
            join = self.new_block()
            if stmt.handlers:
                for h in stmt.handlers:
                    h_b = self.new_block()
                    # any point in the body may raise into the handler
                    for b in body_blocks:
                        b.add_exc_succ(h_b)
                    h_out = self.stmts(h.body, h_b, loops)
                    if h_out is not None:
                        h_out.add_succ(join)
            if stmt.orelse:
                if body_out is not None:
                    body_out = self.stmts(stmt.orelse, body_out, loops)
            if body_out is not None:
                body_out.add_succ(join)
            if stmt.finalbody:
                f_out = self.stmts(stmt.finalbody, join, loops)
                return f_out
            return join
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.expr(item.context_expr, ev)
                if isinstance(stmt, ast.AsyncWith):
                    ev.append((AWAIT, stmt))  # __aenter__
                if item.optional_vars is not None:
                    self._store_target(
                        item.optional_vars, [], item.context_expr, ev
                    )
            out = self.stmts(stmt.body, cur, loops)
            if out is not None and isinstance(stmt, ast.AsyncWith):
                out.events.append((AWAIT, stmt))  # __aexit__
            return out
        if isinstance(stmt, ast.Assert):
            te: list[tuple] = []
            self.expr(stmt.test, te, in_test=True)
            ev.extend(te)
            keys = frozenset(e[1] for e in te if e[0] == READ)
            if keys and any(e[0] == USE for e in te):
                ev.append((GUARD, "assert", keys, stmt))
            self.expr(stmt.msg, ev)
            return cur
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return cur  # separate scope, walked separately
        if isinstance(stmt, getattr(ast, "Match", ())):
            self.expr(stmt.subject, ev)
            join = self.new_block()
            fell = False
            irrefutable = False
            for case in stmt.cases:
                c_b = self.new_block()
                cur.add_succ(c_b)
                c_out = self.stmts(case.body, c_b, loops)
                if c_out is not None:
                    c_out.add_succ(join)
                    fell = True
                if isinstance(case.pattern, ast.MatchAs) and (
                    case.pattern.pattern is None and not case.guard
                ):
                    irrefutable = True  # `case _:` — always matches
            if not irrefutable:
                cur.add_succ(join)  # no case may match
            return join
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    base = self.shared.base_key(t.value)
                    self.expr(t.slice, ev)
                    if base is not None:
                        ev.append(
                            (WRITE, (base, self.shared.sub_key(t.slice)),
                             frozenset(), t)
                        )
            return cur
        # Pass / Global / Nonlocal / Import / anything else: no events
        return cur


def build_cfg(info: FuncInfo, tree: ast.Module) -> tuple[Block, SharedModel]:
    """Lower one async function to (entry block, shared-state model)."""
    shared = SharedModel(tree, info)
    builder = _Builder(info.node, shared)
    entry = builder.build()
    return entry, shared
