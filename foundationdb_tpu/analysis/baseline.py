"""Baseline: freeze pre-existing violations so the gate starts at zero.

The gate's contract is zero-NEW-violations from day one: findings the
tree already had when flowcheck landed live in `analysis/baseline.json`
and don't fail the run; anything not in the file does. Matching is by
(path, rule, message) multiset — line numbers drift with every edit, so
they're recorded for humans but ignored for identity. Fixing a
baselined finding makes its entry stale; `--write-baseline` re-freezes
(the ROADMAP tracks burning the file down to empty).
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

from foundationdb_tpu.analysis.walker import Finding

BASELINE_NAME = "baseline.json"


def baseline_path() -> Path:
    return Path(__file__).resolve().parent / BASELINE_NAME


def load_baseline(path: Path | None = None) -> Counter:
    """(path, rule, message) -> allowed count."""
    p = path or baseline_path()
    if not p.exists():
        return Counter()
    entries = json.loads(p.read_text(encoding="utf-8"))["entries"]
    return Counter(
        (e["path"], e["rule"], e["message"]) for e in entries
    )


def save_baseline(findings: list[Finding], path: Path | None = None) -> None:
    p = path or baseline_path()
    payload = {
        "comment": (
            "Pre-existing flowcheck violations, frozen so the gate is "
            "zero-new-violations. Regenerate with `python -m "
            "foundationdb_tpu.analysis --write-baseline`; the goal is "
            "to burn this file down to empty (ROADMAP open item)."
        ),
        "entries": [
            {
                "path": f.path, "line": f.line,
                "rule": f.rule, "message": f.message,
            }
            for f in sorted(
                findings, key=lambda f: (f.path, f.line, f.rule)
            )
            # a dead ignore is never a pre-existing violation to
            # grandfather: freezing it would permanently blind the
            # stale-suppression audit (string literal: importing the
            # rule id from report.py would cycle)
            if f.rule != "flowcheck.stale-ignore"
        ],
    }
    p.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def split_findings(
    findings: list[Finding], allowed: Counter
) -> tuple[list[Finding], list[Finding], Counter]:
    """(new, baselined, stale): findings beyond their baseline budget,
    findings the baseline absorbs, and baseline entries nothing matched
    (fixed — candidates for --write-baseline)."""
    budget = Counter(allowed)
    new: list[Finding] = []
    baselined: list[Finding] = []
    for f in findings:
        if budget[f.fingerprint()] > 0:
            budget[f.fingerprint()] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = Counter({k: c for k, c in budget.items() if c > 0})
    return new, baselined, stale
