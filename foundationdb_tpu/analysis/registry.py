"""Rule registry: the catalog flowcheck's families register into.

The reference enforces its invariants with purpose-built build tooling —
the actor compiler rejects un-actor-safe control flow, coveragetool
accounts for every CODE_PROBE (flow/actorcompiler, flow/coveragetool).
flowcheck is the same idea collapsed to one registry: each rule family
module registers (a) rule ids with one-line docs (the `--rules` catalog
and the README table are generated from here) and (b) check callables.

Two check shapes:

* file checks — run once per parsed file (`FileContext`); everything a
  single module's AST can decide (determinism, actor safety, JAX
  hazards).
* tree checks — run once over ALL parsed files; cross-file accounting
  (the probe ledger: duplicate declares, used-but-never-declared,
  manifest drift).
"""

from __future__ import annotations

import dataclasses
from typing import Callable


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str        # e.g. "determinism.wall-clock"
    family: str    # e.g. "determinism"
    doc: str       # one line, shown by --rules and in the README catalog


#: rule id -> Rule
RULES: dict[str, Rule] = {}
#: callables(ctx: FileContext) -> None, appending to ctx.findings
FILE_CHECKS: list[Callable] = []
#: callables(ctxs: list[FileContext], options) -> list[Finding]
TREE_CHECKS: list[Callable] = []


def rule(id: str, doc: str) -> str:
    """Register a rule id; returns the id so modules can bind constants."""
    family = id.split(".", 1)[0]
    if id in RULES:
        raise ValueError(f"duplicate rule id {id}")
    RULES[id] = Rule(id=id, family=family, doc=doc)
    return id


def file_check(fn: Callable) -> Callable:
    FILE_CHECKS.append(fn)
    return fn


def tree_check(fn: Callable) -> Callable:
    TREE_CHECKS.append(fn)
    return fn


def load_rules() -> None:
    """Import every rule family (registration happens at import)."""
    from foundationdb_tpu.analysis import (  # noqa: F401
        rules_actor,
        rules_determinism,
        rules_flow,
        rules_jax,
        rules_probes,
        rules_res,
        rules_trace,
        rules_wire,
    )
