"""Resource acquire/release classification: the ownership model the
`res.*` flowcheck family (rules_res.py) path-walks over.

The wire cluster has already needed four review-found connection-close
fixes on error paths, each caught by hand; this module promotes the
bug class to machine-checked structure the way `analysis/cfg.py` did
for stale-reads. It answers three questions, all statically, stdlib
`ast` only:

* **What acquires a resource?** Constructor leaves (`RpcConnection`,
  `RpcServer`, `DiskQueue`, `Popen`, executors), resolved call targets
  (`asyncio.create_task`/`ensure_future`, bare `open()`, socket/
  server factories), `Scheduler.spawn` on a sched-named receiver, and
  — the compositional step — same-file helper functions that RETURN a
  freshly acquired resource (`connect()` in multiprocess.py), so a
  call to the helper is itself an acquire site at the caller.
* **When is it live?** Kinds with an *activation* method
  (`RpcConnection.connect`, `RpcServer.start`) hold no OS resource
  until the activation succeeds — the transport cleans up internally
  on a failed connect — so construction yields a `pending` handle and
  only a successful activation makes it `live`.
* **What releases or transfers it?** Per-kind release methods
  (`.close()`/`.stop()`/`.cancel()`/`close_disk()`...), hand-off to a
  release-stem helper (`_close_all(conns)`), ownership transfer by
  `return`, by call-argument hand-off, by storing into a container or
  onto an object, and — for `self.X = <acquire>` — a release of that
  attribute reachable anywhere in the class (the store-on-self idiom:
  `stop()`/`close()` owns shutdown).

Deliberate precision limits (documented, tests pin the live ones):
collections of resources built by comprehensions are not tracked
element-wise (the scalar acquires around them carry the rules), helper
recognition is same-file only, and `with`-managed acquires are owned
by construction.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

#: release-method leaves per resource kind
RELEASE_METHODS: dict[str, set[str]] = {
    "connection": {"close", "aclose"},
    "server": {"close", "stop"},
    "task": {"cancel"},
    "file": {"close"},
    "diskqueue": {"close_disk", "aclose_disk", "close"},
    "process": {"stop", "terminate", "kill"},
    "executor": {"shutdown"},
    "socket": {"close", "shutdown", "wait_closed", "stop"},
}
RELEASE_METHODS_ANY: set[str] = set().union(*RELEASE_METHODS.values())

#: a call whose func leaf carries one of these stems releases every
#: tracked resource passed to it (`_close_all(conns)`, `stop_roles(x)`)
RELEASE_HELPER_STEMS = (
    "close", "stop", "shutdown", "cancel", "release", "teardown",
)

#: constructor leaf -> (kind, activation method or None). Leaf-exact on
#: purpose: `SimDiskQueue` (the sim twin, no real fd) does not match.
CONSTRUCTORS: dict[str, tuple[str, Optional[str]]] = {
    "RpcConnection": ("connection", "connect"),
    "RpcServer": ("server", "start"),
    "DiskQueue": ("diskqueue", None),
    "Popen": ("process", None),
    "ThreadPoolExecutor": ("executor", None),
    "ProcessPoolExecutor": ("executor", None),
}

#: import-resolved dotted call -> kind; live at construction
RESOLVED_ACQUIRES: dict[str, str] = {
    "asyncio.create_task": "task",
    "asyncio.ensure_future": "task",
    "open": "file",
    "io.open": "file",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "asyncio.start_server": "server",
    "asyncio.start_unix_server": "server",
}

#: dotted receivers whose `.spawn(...)` is a Scheduler task spawn. The
#: DISCARDED-spawn case belongs to `actor.fire-and-forget` (rules_actor
#: has owned it since PR 1) — rules_res must not double-report it.
SPAWN_RECEIVERS = {
    "sched", "scheduler", "_sched",
    "self.sched", "self._sched", "self.scheduler",
}


@dataclasses.dataclass
class Acquire:
    """One acquire site inside one function."""

    kind: str                  # RELEASE_METHODS key
    call: ast.Call             # the acquiring call expression
    #: how the acquired value is bound at the site
    binding: str               # local|self|discard|with|return|arg|other
    name: Optional[str] = None     # local name when binding == "local"
    attr: Optional[str] = None     # self attribute when binding == "self"
    activation: Optional[str] = None  # method that makes it live
    spawned: bool = False      # Scheduler.spawn site (see SPAWN_RECEIVERS)


def _leaf(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def has_release_stem(leaf: Optional[str]) -> bool:
    if not leaf:
        return False
    low = leaf.lower()
    return any(stem in low for stem in RELEASE_HELPER_STEMS)


def walk_scope(fn) -> Iterator[ast.AST]:
    """ast.walk over one function's own scope: nested function/class
    bodies (separate execution scopes, walked separately) excluded."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def acquire_kind(ctx, call: ast.Call,
                 helpers: dict[str, str]) -> Optional[Acquire]:
    """Classify one Call as an acquire site (binding left unset)."""
    leaf = _leaf(call.func)
    if leaf in CONSTRUCTORS:
        kind, activation = CONSTRUCTORS[leaf]
        return Acquire(kind=kind, call=call, binding="",
                       activation=activation)
    resolved = ctx.resolved(call.func)
    if resolved in RESOLVED_ACQUIRES:
        return Acquire(kind=RESOLVED_ACQUIRES[resolved], call=call,
                       binding="")
    if leaf == "spawn":
        recv = ctx.dotted(call.func)
        if recv is not None and recv.rsplit(".", 1)[0] in SPAWN_RECEIVERS:
            return Acquire(kind="task", call=call, binding="",
                           spawned=True)
    if leaf == "create_task" and isinstance(call.func, ast.Attribute):
        recv = _leaf(call.func.value) if isinstance(
            call.func.value, (ast.Name, ast.Attribute)
        ) else None
        if recv is not None and "loop" in recv:
            return Acquire(kind="task", call=call, binding="")
    if leaf in helpers and isinstance(call.func, ast.Name):
        # same-file helper that returns a fresh resource: the returned
        # handle is LIVE (the helper performed any activation itself).
        # Plain-name calls only — `conn.connect()` is an activation
        # method on a handle, not the module helper.
        return Acquire(kind=helpers[leaf], call=call, binding="")
    return None


def _classify_binding(call: ast.Call) -> tuple[str, Optional[str],
                                               Optional[str]]:
    """(binding, local name, self attr) from the acquire's AST parents.

    Climbs through Await/IfExp wrappers (`self._fh = open(p) if p else
    None`) to the binding construct."""
    node: ast.AST = call
    parent = getattr(node, "_fc_parent", None)
    while isinstance(parent, (ast.Await, ast.IfExp, ast.BoolOp)):
        node, parent = parent, getattr(parent, "_fc_parent", None)
    if isinstance(parent, ast.withitem):
        return "with", None, None
    if isinstance(parent, ast.Expr):
        return "discard", None, None
    if isinstance(parent, ast.Return):
        return "return", None, None
    if isinstance(parent, (ast.Assign, ast.AnnAssign)):
        targets = (
            parent.targets if isinstance(parent, ast.Assign)
            else [parent.target]
        )
        t = targets[0]
        if isinstance(t, ast.Name):
            return "local", t.id, None
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
            if t.value.id == "self":
                return "self", None, t.attr
            return "other", None, None
        if isinstance(t, ast.Subscript):
            base = t.value
            if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name
            ) and base.value.id == "self":
                return "self", None, base.attr
            return "other", None, None
        return "other", None, None
    if isinstance(parent, ast.Call) and node is not parent.func:
        return "arg", None, None
    if isinstance(parent, ast.keyword):
        return "arg", None, None
    return "other", None, None


def extract_acquires(ctx, fn, helpers: dict[str, str]) -> list[Acquire]:
    """Every acquire site in one function's own scope, classified."""
    out: list[Acquire] = []
    for node in walk_scope(fn):
        if not isinstance(node, ast.Call):
            continue
        acq = acquire_kind(ctx, node, helpers)
        if acq is None:
            continue
        acq.binding, acq.name, acq.attr = _classify_binding(node)
        out.append(acq)
    return out


def module_helpers(ctx, funcs) -> dict[str, str]:
    """Same-file functions that RETURN a freshly acquired resource:
    simple name -> kind. A call to one of these IS an acquire at the
    caller (ownership transfer by return — multiprocess.py's
    `connect()` shape)."""
    helpers: dict[str, str] = {}
    for info in funcs:
        if "." in info.qualname:
            continue
        fn = info.node
        direct: dict[str, str] = {}
        returned: Optional[str] = None
        # two passes: walk_scope order is arbitrary, and the Return may
        # be visited before the Assign that makes its name an acquire
        nodes = list(walk_scope(fn))
        for node in nodes:
            if isinstance(node, ast.Call):
                acq = acquire_kind(ctx, node, {})
                if acq is None:
                    continue
                binding, name, _attr = _classify_binding(node)
                if binding == "local" and name:
                    direct[name] = acq.kind
                elif binding == "return":
                    returned = acq.kind
        for node in nodes:
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ) and node.value.id in direct:
                returned = direct[node.value.id]
        if returned is not None:
            helpers[fn.name] = returned
    return helpers


def class_released_attrs(cls: ast.ClassDef) -> set[str]:
    """Self attributes some method of the class releases: the
    store-on-self ownership idiom (`self._task = ensure_future(...)`
    is owned iff a `stop()`-reachable release of `self._task` exists).

    Release shapes recognized anywhere in the class body:
    * `self.X.close()` / `.stop()` / `.cancel()` / `close_disk()` ...
      (subscripted receivers like `self._conns[k].close()` included)
    * `self.X` (or a deref of it) passed to a release-stem helper —
      `_close_all(self._conns)`
    * `for c in self.X...: c.close()` — iterate-and-release
    * `del self.X`
    * the null-then-release alias idiom: `t = self.X; self.X = None;
      t.cancel()` (how `_drop_proxy`/`stop` avoid re-entry races)
    """
    out: set[str] = set()

    # per-method alias map: local name -> self attribute it snapshots
    aliases: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ) and isinstance(node.value, ast.Attribute) and isinstance(
            node.value.value, ast.Name
        ) and node.value.value.id == "self":
            aliases[node.targets[0].id] = node.value.attr

    def self_attr_of(node: ast.AST) -> Optional[str]:
        # self.X, self.X[k], self.X.values(), self.X[k].close -> "X":
        # descend to the root, returning the attribute directly on self
        while True:
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) and (
                    node.value.id == "self"
                ):
                    return node.attr
                node = node.value
            elif isinstance(node, ast.Subscript):
                node = node.value
            elif isinstance(node, ast.Call):
                node = node.func
            else:
                return None

    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and (
                node.func.attr in RELEASE_METHODS_ANY
            ):
                attr = self_attr_of(node.func)
                if attr is not None:
                    out.add(attr)
                elif isinstance(node.func.value, ast.Name) and (
                    node.func.value.id in aliases
                ):
                    out.add(aliases[node.func.value.id])
            if has_release_stem(_leaf(node.func)):
                for arg in node.args:
                    attr = self_attr_of(arg)
                    if attr is not None:
                        out.add(attr)
                    elif isinstance(arg, ast.Name) and arg.id in aliases:
                        out.add(aliases[arg.id])
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name
                ) and t.value.id == "self":
                    out.add(t.attr)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            attr = None
            for sub in ast.walk(node.iter):
                if isinstance(sub, ast.Attribute) and isinstance(
                    sub.value, ast.Name
                ) and sub.value.id == "self":
                    attr = sub.attr
                    break
            if attr is None or not isinstance(node.target, ast.Name):
                continue
            tgt = node.target.id
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and sub.func.attr in RELEASE_METHODS_ANY and isinstance(
                    sub.func.value, ast.Name
                ) and sub.func.value.id == tgt:
                    out.add(attr)
                    break
    return out
