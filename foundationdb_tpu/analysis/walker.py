"""AST walking core: file discovery, scopes, suppressions, findings.

flowcheck is stdlib-`ast` only (no new deps, no imports of the scanned
modules — files that need unavailable packages still get checked). One
parse per file produces a `FileContext`; rule families walk the tree
through it and call `ctx.report(...)`, which applies per-line
suppressions before a finding lands.

Scopes — which rules apply where — are path-based and fixed here:

* **sim scope**: code that runs (or may run) under `runtime/flow.py`'s
  deterministic scheduler: `cluster/`, `runtime/`, `sim/`, `testing/`,
  `layers/`, and `resolver.py`. Determinism and actor-safety families
  apply here. Three cluster modules are deliberately exempt because
  they ARE the real-I/O side (never sim-schedulable): see
  `REAL_IO_EXEMPT` below. `wire/` and `crypto/` are outside the scope
  by construction.
* **kernel scope**: `ops/` — the pure-JAX kernel path; the JAX hazard
  family's recompile/host-sync rules apply here (block-in-loop applies
  package-wide).

Suppression: `# flowcheck: ignore[rule]` on the finding's line (or the
line above) suppresses that rule there; the bracket takes a
comma-separated list, a family name suppresses its whole family, and a
bare `# flowcheck: ignore` suppresses everything on the line. Every
suppression should carry a justification in the trailing comment.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

SIM_SCOPE_PREFIXES = ("cluster/", "runtime/", "sim/", "testing/", "layers/")
SIM_SCOPE_FILES = ("resolver.py",)
#: real-I/O modules inside cluster/: never scheduled by the sim loop
#: (multiprocess = real-process harness, multiversion = external asyncio
#: RPC client, monitor = the fdbmonitor-analog OS-process supervisor)
REAL_IO_EXEMPT = (
    "cluster/multiprocess.py",
    "cluster/multiversion.py",
    "cluster/monitor.py",
)
KERNEL_SCOPE_PREFIXES = ("ops/",)

_SUPPRESS_RE = re.compile(r"#\s*flowcheck:\s*ignore(?:\[([^\]]*)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str      # repo-root-relative posix path
    line: int
    rule: str
    message: str

    def fingerprint(self) -> tuple[str, str, str]:
        """Line-number-free identity: baselines must survive drift."""
        return (self.path, self.rule, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line} [{self.rule}] {self.message}"


def _suppressions(source: str) -> dict[int, set[str]]:
    """line number -> set of suppressed rule patterns ('*' = all).

    Tokenize-based: only REAL comments register — a string literal or
    docstring merely mentioning the `# flowcheck: ignore` syntax (this
    module's own docstring does) must not silently suppress findings on
    its line."""
    out: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            if m.group(1) is None:
                pats = {"*"}
            else:
                pats = {
                    p.strip() for p in m.group(1).split(",") if p.strip()
                }
            # a TRAILING marker covers exactly its own line; a marker on
            # a standalone comment line covers the next line (the code it
            # annotates). Anything looser bleeds: a justified trailing
            # ignore on line N must not absorb an unrelated new
            # violation on line N+1.
            standalone = tok.line[: tok.start[1]].strip() == ""
            line = tok.start[0] + 1 if standalone else tok.start[0]
            out.setdefault(line, set()).update(pats)
    except tokenize.TokenError:
        pass  # ast.parse succeeded, so this should be unreachable
    return out


def _matches(rule: str, pattern: str) -> bool:
    return (
        pattern == "*"
        or rule == pattern
        or rule.startswith(pattern + ".")
    )


class FileContext:
    """One parsed file plus everything rules need to judge it."""

    def __init__(self, path: str, source: str):
        self.path = path  # repo-root-relative, posix separators
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = _suppressions(source)
        self.findings: list[Finding] = []
        self.suppressed: list[Finding] = []  # what ignores absorbed
        self.aliases = self._import_aliases()
        # package-relative path for scoping ("cluster/foo.py")
        pkg = "foundationdb_tpu/"
        self.rel = path[len(pkg):] if path.startswith(pkg) else path

    # -- scopes ----------------------------------------------------------

    @property
    def in_sim_scope(self) -> bool:
        if self.rel in REAL_IO_EXEMPT:
            return False
        return self.rel.startswith(SIM_SCOPE_PREFIXES) or (
            self.rel in SIM_SCOPE_FILES
        )

    @property
    def in_kernel_scope(self) -> bool:
        return self.rel.startswith(KERNEL_SCOPE_PREFIXES)

    # -- name resolution -------------------------------------------------

    def _import_aliases(self) -> dict[str, str]:
        """Local name -> canonical dotted origin, from every import in
        the file (function-local imports included): `import time as
        _time` maps `_time`->`time`; `from time import time` maps
        `time`->`time.time`."""
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def dotted(self, node: ast.AST) -> str | None:
        """`a.b.c` for an attribute chain rooted at a Name, else None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def resolved(self, node: ast.AST) -> str | None:
        """dotted() with the first segment mapped through the import
        table, so `_time.sleep` resolves to `time.sleep` and `np.random`
        to `numpy.random`."""
        d = self.dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        origin = self.aliases.get(head)
        if origin is None:
            return d
        return f"{origin}.{rest}" if rest else origin

    # -- reporting -------------------------------------------------------

    def report(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 1)
        f = Finding(path=self.path, line=line, rule=rule, message=message)
        # _suppressions already resolved placement: trailing markers map
        # to their own line, standalone comment lines to the next line
        pats = self.suppressions.get(line)
        if pats and any(_matches(rule, p) for p in pats):
            self.suppressed.append(f)
            return
        self.findings.append(f)


def discover(root: Path) -> list[Path]:
    """Every .py under the package, deterministic order."""
    pkg = root / "foundationdb_tpu"
    return sorted(p for p in pkg.rglob("*.py"))


def parse_file(root: Path, path: Path) -> FileContext:
    rel = path.relative_to(root).as_posix()
    return FileContext(rel, path.read_text(encoding="utf-8"))
