"""Trace-event accounting: the reference's trace schema, statically.

The reference's TraceEvent types are a de-facto schema — `fdbcli
status`, monitoring pipelines and the contrib debugging tools
(commit_debug.py among them) key on the exact UpperCamelCase strings,
and `.detail()` keys are CamelCase throughout flow/Trace.cpp call
sites. These tree-wide rules hold this repo to the same contract:

* trace.lowercase-event — a TraceEvent type (or a trace_batch event
  NAME) that is not UpperCamelCase: the reference's renderers and
  parsers assume the casing.
* trace.dynamic-name — a non-literal event type: statically
  unaccountable, invisible to the manifest (same reasoning as
  probe.dynamic-name).
* trace.detail-case — a literal `.detail()` key that is not CamelCase:
  mixed-case keys fracture downstream queries ("Version" vs "version").
* trace.manifest-drift — `analysis/trace_manifest.json` out of date
  with the tree (run `--write-trace-manifest`): a new event type is a
  schema change and must be a reviewed, deliberate addition.

Exclusions mirror the probe ledger's: `utils/trace.py` (it IS the
machinery — TraceBatch renders caller-supplied names, trace_counters
loops counter keys) and `analysis/` (rule docs name events).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from foundationdb_tpu.analysis import manifest as manifest_mod
from foundationdb_tpu.analysis.registry import rule, tree_check
from foundationdb_tpu.analysis.walker import FileContext, Finding

R_LOWERCASE = rule(
    "trace.lowercase-event",
    "TraceEvent type / trace_batch name is not UpperCamelCase",
)
R_DYNAMIC = rule(
    "trace.dynamic-name",
    "TraceEvent type is not a string literal: statically unaccountable",
)
R_DETAIL = rule(
    "trace.detail-case",
    ".detail() key is not CamelCase like the reference's",
)
R_DRIFT = rule(
    "trace.manifest-drift",
    "trace_manifest.json does not match the tree (--write-trace-manifest)",
)

_CAMEL = re.compile(r"^[A-Z][A-Za-z0-9]*$")


def trace_contexts(ctxs: list[FileContext]) -> list[FileContext]:
    """THE exclusion policy (one copy, like rules_probes'): skip the
    trace machinery itself and this package."""
    return [
        c for c in ctxs
        if c.rel != "utils/trace.py"
        and not c.rel.startswith("analysis/")
    ]


def _chain_root(node: ast.AST) -> ast.AST:
    """Descend a TraceEvent method chain (`.detail(...).log()`) to the
    expression it is rooted at."""
    while (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("detail", "log")
    ):
        node = node.func.value
    return node


def _is_trace_event_call(ctx: FileContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fname = ctx.dotted(node.func)
    return bool(fname) and fname.rsplit(".", 1)[-1] == "TraceEvent"


def _trace_event_names(ctx: FileContext) -> set[str]:
    """Local names bound to a TraceEvent chain (`ev = TraceEvent(...)`,
    `with TraceEvent(...) as e:`) — the anchors for .detail() calls
    that are not chained directly off the construction."""
    names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            if _is_trace_event_call(ctx, _chain_root(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if _is_trace_event_call(
                    ctx, _chain_root(item.context_expr)
                ) and isinstance(item.optional_vars, ast.Name):
                    names.add(item.optional_vars.id)
    return names


def collect_trace_events(ctxs: list[FileContext]):
    """(events, dynamic, details): events maps type -> [(ctx, node)]
    from TraceEvent(...) constructions and add_event/add_attach NAME
    args; dynamic is [(ctx, node)] for non-literal types; details is
    [(ctx, node, key)] for literal .detail keys on TraceEvent chains
    (an unrelated object's .detail() API is not the trace schema's
    business and must not gate-fail the tree)."""
    events: dict[str, list] = {}
    dynamic: list = []
    details: list = []
    for ctx in ctxs:
        ev_names = _trace_event_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = ctx.dotted(node.func)
            leaf = fname.rsplit(".", 1)[-1] if fname else None
            if leaf == "TraceEvent":
                a = node.args[0] if node.args else next(
                    (k.value for k in node.keywords
                     if k.arg == "event_type"),
                    None,
                )
                if a is None:
                    continue  # not a construction shape (re-export)
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    events.setdefault(a.value, []).append((ctx, a))
                else:
                    dynamic.append((ctx, node))
            elif leaf in ("add_event", "add_attach") and node.args:
                # the global trace-batch sink's API; the NAME argument
                # becomes a TraceLog Type when a logger is attached, so
                # it is part of the event schema too
                if "g_trace_batch" not in fname:
                    continue
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    events.setdefault(a.value, []).append((ctx, a))
                else:
                    dynamic.append((ctx, node))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "detail"
                and node.args
            ):
                root = _chain_root(node.func.value)
                anchored = _is_trace_event_call(ctx, root) or (
                    isinstance(root, ast.Name) and root.id in ev_names
                )
                if not anchored:
                    continue
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    details.append((ctx, a, a.value))
    return events, dynamic, details


@tree_check
def check_trace_ledger(ctxs: list[FileContext],
                       manifest_path: Path | None = None) -> list[Finding]:
    findings: list[Finding] = []

    def report(ctx: FileContext, node: ast.AST, rule_id: str,
               message: str) -> None:
        before = len(ctx.findings)
        ctx.report(node, rule_id, message)
        if len(ctx.findings) > before:
            findings.append(ctx.findings.pop())

    events, dynamic, details = collect_trace_events(trace_contexts(ctxs))

    for name, sites in sorted(events.items()):
        if not _CAMEL.match(name):
            ctx, node = sites[0]
            report(
                ctx, node, R_LOWERCASE,
                f"trace event {name!r} is not UpperCamelCase",
            )
    for ctx, node in dynamic:
        report(ctx, node, R_DYNAMIC, "non-literal trace event type")
    for ctx, node, key in details:
        if not _CAMEL.match(key):
            report(
                ctx, node, R_DETAIL,
                f"detail key {key!r} is not CamelCase",
            )

    tree_events = {
        name: sites[0][0].path for name, sites in events.items()
    }
    stored = manifest_mod.load_trace_manifest(manifest_path)
    if stored != tree_events:
        missing = sorted(set(tree_events) - set(stored))
        stale = sorted(set(stored) - set(tree_events))
        detail = []
        if missing:
            detail.append(f"not in manifest: {missing[:4]}")
        if stale:
            detail.append(f"stale in manifest: {stale[:4]}")
        findings.append(Finding(
            path="foundationdb_tpu/analysis/"
                 + manifest_mod.TRACE_MANIFEST_NAME,
            line=1,
            rule=R_DRIFT,
            message="; ".join(detail) or "emitting files moved",
        ))
    return findings


def tree_trace_manifest(ctxs: list[FileContext]) -> dict[str, str]:
    """event type -> first emitting file, for --write-trace-manifest."""
    events, _dyn, _det = collect_trace_events(trace_contexts(ctxs))
    return {name: sites[0][0].path for name, sites in events.items()}
