"""Probe accounting: the coveragetool role, statically.

The reference's coveragetool walks the source for CODE_PROBE sites and
CI asserts every one fires across the ensemble (flow/coveragetool,
flow/CodeProbe.h). The runtime side exists here (`utils/probes.py`,
soak's missed-probe report) — but `code_probe()` auto-registers
defensively, so an UNDECLARED probe silently opts out of the
every-probe-must-fire contract: if its path goes dark, nothing notices.
These tree-wide rules close that hole:

* probe.undeclared — a `code_probe(cond, "name")` whose name no
  `declare(...)` registers: invisible to missed-probe accounting.
* probe.duplicate — one name declared at two sites: the ledger can't
  attribute it, and a rename that misses one site splits the probe.
* probe.dynamic-name — a non-literal name argument: statically
  unaccountable (the reference requires literal strings for the same
  reason).
* probe.manifest-drift — `analysis/probe_manifest.json` out of date
  with the tree (run `--write-manifest`).
"""

from __future__ import annotations

import ast
from pathlib import Path

from foundationdb_tpu.analysis import manifest as manifest_mod
from foundationdb_tpu.analysis.registry import rule, tree_check
from foundationdb_tpu.analysis.walker import FileContext, Finding

R_UNDECLARED = rule(
    "probe.undeclared",
    "code_probe name never declare()d: invisible to missed-probe "
    "accounting",
)
R_DUPLICATE = rule(
    "probe.duplicate",
    "probe name declared at more than one site",
)
R_DYNAMIC = rule(
    "probe.dynamic-name",
    "probe name is not a string literal: statically unaccountable",
)
R_DRIFT = rule(
    "probe.manifest-drift",
    "probe_manifest.json does not match the tree (--write-manifest)",
)


def probe_contexts(ctxs: list[FileContext]) -> list[FileContext]:
    """The contexts probe accounting applies to — THE exclusion policy,
    shared by the gate's tree check, --write-manifest, and the
    scripts/probe_scan.py CLI (one copy or they drift): skip probes.py
    itself (it defines declare/code_probe) and this package (rule docs
    mention the callables by name)."""
    return [
        c for c in ctxs
        if c.rel != "utils/probes.py"
        and not c.rel.startswith("analysis/")
    ]


def manifest_of(declares: dict[str, list]) -> dict[str, str]:
    """name -> declaring file, from a collect_probes declares map."""
    return {name: sites[0][0].path for name, sites in declares.items()}


def collect_probes(ctxs: list[FileContext]):
    """(declares, uses, dynamic): declares/uses map name -> [(ctx, node)],
    dynamic is [(ctx, node, kind)] for non-literal name args."""
    declares: dict[str, list] = {}
    uses: dict[str, list] = {}
    dynamic: list = []
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = ctx.dotted(node.func)
            leaf = fname.rsplit(".", 1)[-1] if fname else None
            if leaf == "declare":
                args = list(node.args) + [k.value for k in node.keywords]
                for a in args:
                    if isinstance(a, ast.Constant) and isinstance(
                        a.value, str
                    ):
                        declares.setdefault(a.value, []).append((ctx, a))
                    else:
                        dynamic.append((ctx, node, "declare"))
            elif leaf == "code_probe":
                # the name may arrive positionally or as name=...; a
                # call where it is neither a literal nor findable is
                # dynamic — it must not silently escape the ledger
                a = node.args[1] if len(node.args) >= 2 else next(
                    (k.value for k in node.keywords if k.arg == "name"),
                    None,
                )
                if a is None and len(node.args) < 2 and not node.keywords:
                    continue  # not a real call shape (e.g. re-export)
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    uses.setdefault(a.value, []).append((ctx, node))
                else:
                    dynamic.append((ctx, node, "code_probe"))
    return declares, uses, dynamic


@tree_check
def check_probe_ledger(ctxs: list[FileContext],
                       manifest_path: Path | None = None) -> list[Finding]:
    findings: list[Finding] = []

    def report(ctx: FileContext, node: ast.AST, rule_id: str,
               message: str) -> None:
        before = len(ctx.findings)
        ctx.report(node, rule_id, message)
        # move from the per-file list into the tree result
        if len(ctx.findings) > before:
            findings.append(ctx.findings.pop())

    declares, uses, dynamic = collect_probes(probe_contexts(ctxs))

    for name, sites in sorted(declares.items()):
        if len(sites) > 1:
            where = ", ".join(c.path for c, _n in sites[1:])
            ctx, node = sites[0]
            report(
                ctx, node, R_DUPLICATE,
                f"probe {name!r} also declared in {where}",
            )
    for name, sites in sorted(uses.items()):
        if name not in declares:
            ctx, node = sites[0]
            report(
                ctx, node, R_UNDECLARED,
                f"code_probe({name!r}) has no declare() site",
            )
    for ctx, node, kind in dynamic:
        report(
            ctx, node, R_DYNAMIC,
            f"{kind}() with a non-literal probe name",
        )

    # manifest drift: compare the tree's ledger to the checked-in file
    tree_manifest = manifest_of(declares)
    stored = manifest_mod.load_manifest(manifest_path)
    if stored != tree_manifest:
        missing = sorted(set(tree_manifest) - set(stored))
        stale = sorted(set(stored) - set(tree_manifest))
        detail = []
        if missing:
            detail.append(f"not in manifest: {missing[:4]}")
        if stale:
            detail.append(f"stale in manifest: {stale[:4]}")
        findings.append(Finding(
            path="foundationdb_tpu/analysis/" + manifest_mod.MANIFEST_NAME,
            line=1,
            rule=R_DRIFT,
            message="; ".join(detail) or "declaring files moved",
        ))
    return findings


def tree_manifest(ctxs: list[FileContext]) -> dict[str, str]:
    """name -> declaring file, for --write-manifest."""
    declares, _uses, _dyn = collect_probes(probe_contexts(ctxs))
    return manifest_of(declares)
