"""res.* — resource-ownership leak pass over the CFG substrate.

The compositional ownership analysis (the RacerD/Infer discipline
scaled to this codebase): every function that acquires a resource
(`analysis/resource_registry.py` decides what acquires, releases,
activates, and transfers) gets its CFG path-walked — exception and
cancellation edges included, the PR-3 lowering — and any path on which
a live resource reaches an exit unreleased and unowned is a finding.

State machine per tracked local handle, walked over `cfg.build_cfg`
blocks (sync functions included — `open()`/Popen live in plain defs):

    DEF of an acquire        -> pending (activation kinds) or live
    successful activation    -> live     (`await conn.connect()`)
    release method / helper  -> released (`.close()`, `_close_all(c)`)
    return / arg / store     -> transferred (ownership moved; untracked)
    rebind while live        -> res.leak-on-error-path
    exit (return/raise/fall-off) while live -> res.leak-on-error-path
    unprotected await while live            -> res.leak-on-error-path
    release while released (same block)     -> res.double-close
    deref while released                    -> res.transfer-then-use

Exceptions at an *activation* await propagate the PRE state (pending —
the transport cleans up its own half-open sockets on a failed connect),
so `conn = RpcConnection(...); await conn.connect()` with no try is NOT
a finding; an exception at any other await while live escapes with the
handle live, which is exactly the bug class the wire cluster needed
four hand-caught review fixes for.

The CFG lowers `finally` bodies after the join only (cfg.py's
documented conservative edge), so try/finally protection is checked
syntactically: an enclosing `try` whose finalbody releases the handle
protects its awaits/returns/raises; an enclosing `try` with handlers
defers to the exception-edge path walk instead.

Deliberate conservative choices (README "resource ownership"):
* tasks use a syntactic ever-owned check, not the path walk — a task
  handle is owned the moment anything derefs, awaits, cancels, stores,
  or hands it off (`w.done` into an all_of list is ownership);
  `Scheduler.spawn` discards stay with `actor.fire-and-forget`.
* cancellation-tight (BaseException) handlers are not required: any
  handler or releasing finalbody counts as protection.
* resource collections built by comprehensions are not tracked
  element-wise; same-file helper returns are the interprocedural step.
"""

from __future__ import annotations

import ast
from typing import Optional

from foundationdb_tpu.analysis import cfg
from foundationdb_tpu.analysis import resource_registry as rr
from foundationdb_tpu.analysis.registry import file_check, rule

RULE_LEAK = rule(
    "res.leak-on-error-path",
    "an acquired resource (connection/server/file/process/queue) "
    "reaches a function exit — return, raise, fall-off, rebind, or an "
    "exception at an unprotected await — unreleased and unowned",
)
RULE_TASK = rule(
    "res.task-unowned",
    "a spawned task nothing owns: discarded create_task/ensure_future, "
    "or a bound task handle never awaited/cancelled/stored/handed off",
)
RULE_DOUBLE = rule(
    "res.double-close",
    "one handle released twice on the same straight-line path with no "
    "re-acquire between",
)
RULE_USE = rule(
    "res.transfer-then-use",
    "a handle dereferenced after its release on the same path "
    "(use-after-close)",
)

#: path-walk state-space bound per function (visited (block, state)
#: pairs) — far above any real function; a backstop, not a budget
_WALK_BUDGET = 60_000


def _display(info: cfg.FuncInfo) -> str:
    cls = info.class_node.name + "." if info.class_node is not None else ""
    return f"{cls}{info.qualname}"


def _acquire_for(acqs: list[rr.Acquire],
                 value_node: ast.AST) -> Optional[rr.Acquire]:
    """The acquire whose call the DEF's value expression contains."""
    for a in acqs:
        if value_node is a.call:
            return a
        for sub in ast.walk(value_node):
            if sub is a.call:
                return a
    return None


def _classify_use(node: ast.Name, kind: str, activation: Optional[str]
                  ) -> tuple[str, Optional[ast.Await]]:
    """(action, enclosing-await) for one Load use of a tracked handle:
    release | activate | deref | transfer | none."""
    parent = getattr(node, "_fc_parent", None)
    if isinstance(parent, ast.Attribute) and parent.value is node:
        gp = getattr(parent, "_fc_parent", None)
        if isinstance(gp, ast.Call) and gp.func is parent:
            ggp = getattr(gp, "_fc_parent", None)
            awaited = ggp if isinstance(ggp, ast.Await) else None
            leaf = parent.attr
            if leaf in rr.RELEASE_METHODS.get(kind, set()):
                return "release", awaited
            if activation is not None and leaf == activation:
                return "activate", awaited
            return "deref", awaited
        return "deref", None
    if isinstance(parent, ast.Call) and node is not parent.func:
        if rr.has_release_stem(rr._leaf(parent.func)):
            gp = getattr(parent, "_fc_parent", None)
            return "release", gp if isinstance(gp, ast.Await) else None
        return "transfer", None
    if isinstance(parent, ast.keyword):
        return "transfer", None
    if isinstance(parent, ast.Await) and parent.value is node:
        return "transfer", None  # awaiting the handle consumes it
    if isinstance(parent, ast.Return):
        return "transfer", None
    if isinstance(parent, (ast.Assign, ast.AnnAssign)):
        return "transfer", None  # aliased / stored somewhere persistent
    if isinstance(parent, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
        return "transfer", None  # placed into a container literal
    if isinstance(parent, ast.Subscript) and parent.value is node:
        return "deref", None
    return "none", None


def _releases_name(stmts: list[ast.stmt], name: str) -> bool:
    for s in stmts:
        for node in ast.walk(s):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ) and f.value.id == name and f.attr in rr.RELEASE_METHODS_ANY:
                return True
            if rr.has_release_stem(rr._leaf(f)) and any(
                isinstance(a, ast.Name) and a.id == name
                for a in node.args
            ):
                return True
    return False


def _protected(node: ast.AST, name: str, info: cfg.FuncInfo,
               exception: bool) -> bool:
    """An enclosing try protects this exit for `name`: a finalbody that
    releases it always does; for exception exits, any handler does too
    (the exception-edge path walk owns that continuation)."""
    prev: ast.AST = node
    cur = getattr(node, "_fc_parent", None)
    while cur is not None and prev is not info.node:
        if isinstance(cur, ast.Try):
            in_body = any(prev is s for s in cur.body) or any(
                prev is s for s in cur.orelse
            )
            if in_body:
                if exception and cur.handlers:
                    return True
                if _releases_name(cur.finalbody, name):
                    return True
        prev, cur = cur, getattr(cur, "_fc_parent", None)
    return False


def _ever_owned(fn, name: str, binding_call: ast.Call) -> bool:
    """Syntactic task-ownership: any Load use of the handle besides its
    own binding (deref, await, cancel, hand-off, container add)."""
    for node in rr.walk_scope(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, ast.Load
        ) and node.id == name:
            return True
    return False


def _walk_function(ctx, info: cfg.FuncInfo, acqs: list[rr.Acquire]
                   ) -> None:
    tracked: dict[str, list[rr.Acquire]] = {}
    for a in acqs:
        if a.binding == "local" and a.name and a.kind != "task":
            tracked.setdefault(a.name, []).append(a)
    if not tracked:
        return
    entry, _shared = cfg.build_cfg(info, ctx.tree)
    findings: dict[tuple[str, str], tuple[ast.AST, str]] = {}
    benign_awaits: set[int] = set()
    disp = _display(info)

    def flag(rule_id: str, name: str, node: ast.AST, msg: str) -> None:
        findings.setdefault((rule_id, name), (node, msg))

    # state[name] = (status, kind, activation, released-in-block-id)
    stack: list[tuple[cfg.Block, frozenset]] = [(entry, frozenset())]
    visited: set[tuple[int, frozenset]] = set()
    budget = _WALK_BUDGET
    while stack and budget > 0:
        budget -= 1
        block, fstate = stack.pop()
        key = (id(block), fstate)
        if key in visited:
            continue
        visited.add(key)
        state = dict(fstate)
        escapes: list[dict] = [dict(state)] if fstate else []
        infeasible = False
        for ev in block.events:
            k = ev[0]
            if k == cfg.NARROW:
                # every tracked status (pending/live/released) means the
                # name holds a real object — the `x is None` branch of
                # this path cannot execute; kill it
                if ev[2] == "none" and ev[1] in state:
                    infeasible = True
                    break
            elif k == cfg.DEF:
                name, node = ev[1], ev[3]
                if name not in tracked:
                    continue
                acq = _acquire_for(tracked[name], node)
                cur = state.get(name)
                if cur is not None and cur[0] == "live":
                    flag(
                        RULE_LEAK, name, node,
                        f"{disp}: rebinds `{name}` while the previous "
                        f"{cur[1]} is still live and unreleased",
                    )
                if acq is not None:
                    state[name] = (
                        "pending" if acq.activation else "live",
                        acq.kind, acq.activation, 0,
                    )
                else:
                    state.pop(name, None)
            elif k == cfg.USE:
                name, node = ev[1], ev[3]
                cur = state.get(name)
                if cur is None or not isinstance(node, ast.Name):
                    continue
                status, kind, activation, relb = cur
                action, awaited = _classify_use(node, kind, activation)
                if action == "release":
                    if status == "released" and relb == id(block):
                        flag(
                            RULE_DOUBLE, name, node,
                            f"{disp}: `{name}` ({kind}) released twice "
                            "on the same path with no re-acquire "
                            "between",
                        )
                    state[name] = ("released", kind, activation,
                                   id(block))
                    # best-effort close: a release attempt releases even
                    # on its own exception edge (`try: await c.close()
                    # except: pass` must be clean) — rewrite the live
                    # snapshots this block already captured
                    for e in escapes:
                        ec = e.get(name)
                        if ec is not None and ec[0] == "live":
                            e[name] = state[name]
                    if awaited is not None:
                        benign_awaits.add(id(awaited))
                elif action == "activate":
                    if status == "pending":
                        # an exception AT the activation escapes the
                        # PRE state: nothing live yet
                        escapes.append(dict(state))
                        state[name] = ("live", kind, activation, 0)
                    if awaited is not None:
                        benign_awaits.add(id(awaited))
                elif action == "transfer":
                    state.pop(name, None)
                elif action == "deref":
                    if status == "released":
                        flag(
                            RULE_USE, name, node,
                            f"{disp}: `{name}` ({kind}) used after "
                            "being closed/released on this path",
                        )
            elif k == cfg.AWAIT:
                node = ev[1]
                if id(node) in benign_awaits or not state:
                    continue
                escapes.append(dict(state))
                if not block.exc_succs:
                    for name, cur in state.items():
                        if cur[0] != "live":
                            continue
                        if _protected(node, name, info, exception=True):
                            continue
                        flag(
                            RULE_LEAK, name, node,
                            f"{disp}: `{name}` ({cur[1]}) is live "
                            "across `await` with no enclosing "
                            "try/finally releasing it — an exception "
                            "here leaks it",
                        )
            elif k in (cfg.RETURN, cfg.RAISE):
                node = ev[1] if len(ev) > 1 else info.node
                is_raise = k == cfg.RAISE
                if is_raise and block.exc_succs:
                    continue  # the handler path walk owns it
                for name, cur in state.items():
                    if cur[0] != "live":
                        continue
                    if _protected(node, name, info, exception=is_raise):
                        continue
                    flag(
                        RULE_LEAK, name, node,
                        f"{disp}: `{name}` ({cur[1]}) still unreleased "
                        + ("when raising" if is_raise else "at return"),
                    )
        if infeasible:
            continue  # `x is None` branch while x holds the resource
        fr = frozenset(state.items())
        for s in block.succs:
            stack.append((s, fr))
        if block.exc_succs:
            escapes.append(dict(state))
            for es in escapes:
                for h in block.exc_succs:
                    stack.append((h, frozenset(es.items())))
        if not block.succs and not block.terminated:
            # fall-off function exit (finalbody events, if any, were
            # already lowered into this path by the CFG)
            for name, cur in state.items():
                if cur[0] != "live":
                    continue
                acq = tracked[name][0]
                flag(
                    RULE_LEAK, name, acq.call,
                    f"{disp}: `{name}` ({cur[1]}) may reach the end of "
                    "the function unreleased",
                )
    for (rule_id, _name), (node, msg) in findings.items():
        ctx.report(node, rule_id, msg)


@file_check
def check_resource_ownership(ctx) -> None:
    if ctx.rel.startswith("analysis/"):
        return  # the analyzer's own fixtures/docs mention acquire idioms
    funcs = list(cfg.iter_functions(ctx.tree))
    if not funcs:
        return
    helpers = rr.module_helpers(ctx, funcs)
    released_attr_cache: dict[int, set[str]] = {}
    for info in funcs:
        acqs = rr.extract_acquires(ctx, info.node, helpers)
        if not acqs:
            continue
        disp = _display(info)
        for a in acqs:
            if a.kind == "task":
                if a.binding == "discard" and not a.spawned:
                    ctx.report(
                        a.call, RULE_TASK,
                        f"{disp}: task discarded at spawn — nothing "
                        "can await, cancel, or observe its error",
                    )
                elif a.binding == "self" and info.class_node is not None:
                    rel = released_attr_cache.setdefault(
                        id(info.class_node),
                        rr.class_released_attrs(info.class_node),
                    )
                    if a.attr not in rel:
                        ctx.report(
                            a.call, RULE_TASK,
                            f"{disp}: `self.{a.attr}` task stored on "
                            "self but no method of "
                            f"{info.class_node.name} ever cancels or "
                            "awaits it (no release reachable from "
                            "stop()/close())",
                        )
                elif a.binding == "local" and a.name:
                    if not _ever_owned(info.node, a.name, a.call):
                        ctx.report(
                            a.call, RULE_TASK,
                            f"{disp}: `{a.name}` task bound but never "
                            "awaited, cancelled, or handed off",
                        )
            elif a.binding == "self" and info.class_node is not None:
                rel = released_attr_cache.setdefault(
                    id(info.class_node),
                    rr.class_released_attrs(info.class_node),
                )
                if a.attr not in rel:
                    ctx.report(
                        a.call, RULE_LEAK,
                        f"{disp}: `self.{a.attr}` ({a.kind}) stored on "
                        "self but no method of "
                        f"{info.class_node.name} ever releases it (no "
                        "close/stop reachable from shutdown)",
                    )
            elif a.binding == "discard" and a.activation is None:
                ctx.report(
                    a.call, RULE_LEAK,
                    f"{disp}: {a.kind} acquired and immediately "
                    "discarded — nothing can ever release it",
                )
        _walk_function(ctx, info, acqs)
