"""JAX hazard rules: the kernel path must stay recompile- and sync-free.

The ≥5x plateau work (G-independent compile, VERDICT r5 task 1) dies
quietly on exactly these: a `float()` on a traced value forces a
device->host sync inside the step, a data-dependent output shape forces
a recompile per shape, a `block_until_ready` inside a dispatch loop
serializes what double-buffering was built to overlap
(models/conflict_set.resolve_group_stream). None of them throw — they
just erase the throughput the kernel was rewritten for.

Rules:

* jax.host-sync (kernel scope, `ops/`) — `float()/int()/bool()` on a
  non-literal, and `.item()` / `np.asarray()`-style escapes: each one
  is a device fence inside code that must stay traceable.
* jax.host-numpy (kernel scope) — host `numpy.*` calls inside the pure
  kernel modules: silently moves the computation off-device.
* jax.data-dep-shape (kernel scope) — `jnp.nonzero/unique/argwhere/
  flatnonzero/compress/extract` and one-argument `jnp.where`: output
  shape depends on values, so every batch recompiles.
* jax.block-in-loop (package-wide) — `.block_until_ready()` inside a
  for/while body: fences the pipeline once per iteration.
"""

from __future__ import annotations

import ast

from foundationdb_tpu.analysis.registry import file_check, rule
from foundationdb_tpu.analysis.walker import FileContext

R_HOST_SYNC = rule(
    "jax.host-sync",
    "float()/int()/bool()/.item() on a traced value forces a "
    "device->host sync in the kernel path",
)
R_HOST_NUMPY = rule(
    "jax.host-numpy",
    "host numpy call inside a kernel module moves compute off-device",
)
R_DATA_DEP = rule(
    "jax.data-dep-shape",
    "data-dependent output shape forces a recompile per batch",
)
R_BLOCK_LOOP = rule(
    "jax.block-in-loop",
    "block_until_ready inside a loop fences the dispatch pipeline "
    "every iteration",
)

_CASTS = {"float", "int", "bool"}
_DATA_DEP_LEAVES = {
    "nonzero", "unique", "argwhere", "flatnonzero", "compress", "extract",
}


def _is_literal(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant)
    )


@file_check
def check_jax_hazards(ctx: FileContext) -> None:
    _walk(ctx, ctx.tree, loop_depth=0)


def _walk(ctx: FileContext, node: ast.AST, loop_depth: int) -> None:
    for child in ast.iter_child_nodes(node):
        inner = loop_depth
        if isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
            inner += 1
        if isinstance(child, ast.Call):
            _check_call(ctx, child, loop_depth)
        _walk(ctx, child, inner)


def _check_call(ctx: FileContext, call: ast.Call, loop_depth: int) -> None:
    fname = ctx.resolved(call.func)
    leaf = ctx.dotted(call.func)
    leaf = leaf.rsplit(".", 1)[-1] if leaf else None
    if leaf == "block_until_ready" and loop_depth > 0:
        ctx.report(
            call, R_BLOCK_LOOP,
            "block_until_ready() inside a loop body",
        )
    if not ctx.in_kernel_scope:
        return
    if (
        isinstance(call.func, ast.Name)
        and call.func.id in _CASTS
        and call.args
        and not _is_literal(call.args[0])
    ):
        ctx.report(
            call, R_HOST_SYNC,
            f"{call.func.id}() on a non-literal value",
        )
    elif leaf == "item" and not call.args:
        ctx.report(call, R_HOST_SYNC, ".item() on a device value")
    elif fname is not None:
        if fname.startswith("numpy.") and not fname.startswith(
            "numpy.random."
        ):
            # host numpy is already wrong here regardless of which op;
            # one finding per call (the data-dep rule covers jax.numpy)
            ctx.report(call, R_HOST_NUMPY, f"call to {fname}()")
        elif fname.startswith("jax.numpy."):
            jleaf = fname.rsplit(".", 1)[-1]
            if jleaf in _DATA_DEP_LEAVES:
                ctx.report(
                    call, R_DATA_DEP, f"{jleaf}() output shape is data-"
                    "dependent",
                )
            elif jleaf == "where" and len(call.args) == 1:
                ctx.report(
                    call, R_DATA_DEP,
                    "one-argument where() output shape is data-dependent",
                )
