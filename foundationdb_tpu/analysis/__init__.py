"""flowcheck: the static enforcement layer the reference gets from its
build tooling (actor compiler + coveragetool), rebuilt as an AST linter.

Five rule families over the whole package (stdlib `ast`, no imports of
the scanned code): determinism (no wall clock / unseeded entropy / raw
asyncio in sim-schedulable actors), actor safety (no silently escaping
errors), JAX hazards (no recompiles or host syncs in the kernel path),
probe accounting (every CODE_PROBE declared exactly once, manifest
pinned), and — v2 — the `flow.*` dataflow pass over per-`async def`
control-flow graphs (cfg.py): stale reads across a wait(), RMWs split
across yield points, and invariant checks never repeated after one
(rules_flow.py). The gate also audits suppressions themselves: a
`# flowcheck: ignore` that absorbs nothing is a finding. Run the gate
with `python -m foundationdb_tpu.analysis`; see the README's
"flowcheck" sections for baselining, suppressions, and the runtime
counterpart (the scheduler's interleaving auditor).
"""

from foundationdb_tpu.analysis.report import (  # noqa: F401
    AnalysisResult,
    analyze_source,
    run_analysis,
)
from foundationdb_tpu.analysis.walker import Finding  # noqa: F401
