"""flowcheck: the static enforcement layer the reference gets from its
build tooling (actor compiler + coveragetool), rebuilt as an AST linter.

Four rule families over the whole package (stdlib `ast`, no imports of
the scanned code): determinism (no wall clock / unseeded entropy / raw
asyncio in sim-schedulable actors), actor safety (no silently escaping
errors), JAX hazards (no recompiles or host syncs in the kernel path),
and probe accounting (every CODE_PROBE declared exactly once, manifest
pinned). Run the gate with `python -m foundationdb_tpu.analysis`; see
the README's "flowcheck" section for baselining and suppressions.
"""

from foundationdb_tpu.analysis.report import (  # noqa: F401
    AnalysisResult,
    analyze_source,
    run_analysis,
)
from foundationdb_tpu.analysis.walker import Finding  # noqa: F401
