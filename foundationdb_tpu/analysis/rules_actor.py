"""Actor-safety rules: errors must not escape the scheduler silently.

The round-5 soak re-run printed 264 unhandled-actor-error tracebacks
(`config_db.set` racing coordinator outages) and still passed green —
the exact failure class the reference's actor compiler makes structurally
loud (an ACTOR's error always lands in its returned Future; dropping
that future is visible in the code). These rules make the Python port's
equivalents visible:

* actor.fire-and-forget — a bare `spawn(...)` statement discards the
  Task: nobody can ever observe its error. Keep the handle and await
  `task.done` (or suppress with a justification naming how errors
  surface — the scheduler's unhandled-error accounting turns them into
  soak failures either way).
* actor.unawaited-future — a bare `...delay(...)` statement (a no-op
  bug: the future is never awaited) or a bare call to a local
  `async def` (builds a coroutine that never runs).
* actor.swallow — `except:` / `except Exception:` / `except
  BaseException:` whose body is ONLY pass/continue/`...`: the shape
  that turns a real fault into silence. Narrow the type, or log before
  continuing.
"""

from __future__ import annotations

import ast

from foundationdb_tpu.analysis.registry import file_check, rule
from foundationdb_tpu.analysis.walker import FileContext

R_FIRE_FORGET = rule(
    "actor.fire-and-forget",
    "spawned Task discarded; its error can escape the scheduler unseen",
)
R_UNAWAITED = rule(
    "actor.unawaited-future",
    "future/coroutine created and never awaited (statement has no effect)",
)
R_SWALLOW = rule(
    "actor.swallow",
    "broad except whose body only passes: faults become silence",
)

_BROAD = {"Exception", "BaseException"}


def _local_async_defs(tree: ast.Module) -> set[str]:
    return {
        n.name for n in ast.walk(tree) if isinstance(n, ast.AsyncFunctionDef)
    }


def _only_passes(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / `...`
        return False
    return True


@file_check
def check_actor_safety(ctx: FileContext) -> None:
    if not ctx.in_sim_scope:
        return
    async_defs = _local_async_defs(ctx.tree)

    def classify_call(node: ast.AST, call: ast.Call, where: str) -> None:
        fname = ctx.dotted(call.func)
        leaf = fname.rsplit(".", 1)[-1] if fname else None
        if leaf == "spawn":
            ctx.report(
                node, R_FIRE_FORGET,
                f"bare spawn(){where}: keep the Task and observe "
                "task.done",
            )
        elif leaf == "delay":
            ctx.report(
                node, R_UNAWAITED,
                f"bare delay(){where}: the returned Future is never "
                "awaited",
            )
        elif (
            isinstance(call.func, ast.Name)
            and call.func.id in async_defs
        ):
            ctx.report(
                node, R_UNAWAITED,
                f"bare call to async def {call.func.id}{where}: "
                "coroutine is never scheduled",
            )

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            classify_call(node, node.value, "")
        elif isinstance(node, ast.Expr) and isinstance(
            node.value,
            (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp),
        ):
            # the comprehension blind spot: a bare statement like
            # `[worker() for w in ws]` builds a coroutine (or discards
            # a Task/Future) per element with nobody ever awaiting —
            # the same bug as the bare call, once per element
            comp = node.value
            elts = (
                (comp.key, comp.value) if isinstance(comp, ast.DictComp)
                else (comp.elt,)
            )
            for elt in elts:
                if isinstance(elt, ast.Call):
                    classify_call(node, elt, " inside a bare comprehension")
        elif isinstance(node, ast.ExceptHandler):
            broad = _broad_name(node.type)
            if broad is not None and _only_passes(node.body):
                ctx.report(
                    node, R_SWALLOW,
                    f"{broad}: pass — narrow the type or log the fault",
                )


def _broad_name(type_node) -> "str | None":
    """Human-readable label if this except clause is broad — bare,
    Exception/BaseException by any spelling (Name, builtins.Exception),
    or a tuple CONTAINING one (a one-character wrapper must not defeat
    the rule). None when narrow."""
    if type_node is None:
        return "bare except"
    if isinstance(type_node, ast.Name) and type_node.id in _BROAD:
        return f"except {type_node.id}"
    if isinstance(type_node, ast.Attribute) and type_node.attr in _BROAD:
        return f"except ...{type_node.attr}"
    if isinstance(type_node, ast.Tuple):
        for el in type_node.elts:
            inner = _broad_name(el)
            if inner is not None:
                return inner.replace("except", "except tuple containing", 1)
    return None
