"""The `flow.*` family: stale state across a wait(), found statically.

PR 2's model checker caught the real thing — a GC floor validated
before `_wait_for_version` was silently stale after it
(cluster/storage.py, soak seeds 1122/1171/2036), and data distribution
carried the same latent shape. The actor compiler's first lesson is
that *all state may change across a wait()*; these rules turn that one
hand-found bug into a machine-checked bug class over the CFGs cfg.py
builds per `async def`:

* flow.stale-read-across-wait — a validation guard (`if req <
  self.shared: raise`) or a local snapshot of shared mutable state
  taken before an `await` still governs behavior after it, with no
  re-read of that state past the yield point. The exact
  storage.py/_wait_for_version shape: the fix is to re-read (and
  re-raise) after the last await, which is precisely what silences the
  rule.
* flow.rmw-across-wait — a read-modify-write of shared state split
  across a yield point: `v = self.x` … `await …` … `self.x = f(v)`
  (or the one-statement form `self.x = await f(self.x)`). The
  interleaved writer's update is lost.
* flow.guard-not-rechecked — an invariant-check call
  (`self._check_*(…, request_arg, …)`) or a shared-state assert whose
  subject is awaited past without an identical re-check afterwards —
  the double-`_check_shard_floor` discipline in storage.py's read
  path, enforced.

Path semantics (first-await discipline): a finding needs a path from
the read/guard/check through a yield point to a function exit on which
the FIRST await crossed is never followed by the re-read/re-check.
A path that re-validates after its first await is clean there — any
LATER await it then crosses without re-validating is a separate
finding anchored at the re-validation site, so each missing re-check
reports exactly once. Paths that end in `raise` don't count (refusing
to serve can't serve stale state).
"""

from __future__ import annotations

import ast

from foundationdb_tpu.analysis import cfg
from foundationdb_tpu.analysis.cfg import (
    AWAIT,
    CHECK,
    DEF,
    GUARD,
    RAISE,
    READ,
    RETURN,
    STMT,
    USE,
    WRITE,
    Block,
    keys_conflict,
)
from foundationdb_tpu.analysis.registry import file_check, rule
from foundationdb_tpu.analysis.walker import FileContext

R_STALE = rule(
    "flow.stale-read-across-wait",
    "shared state read before an await still governs behavior after "
    "it; re-read/re-validate past the yield point",
)
R_RMW = rule(
    "flow.rmw-across-wait",
    "read-modify-write of shared state split across a yield point "
    "(interleaved writers' updates are lost)",
)
R_GUARD = rule(
    "flow.guard-not-rechecked",
    "invariant check whose subject is awaited past without an "
    "identical re-check after the wait",
)

#: paths explored per origin event before giving up (CFGs here are tiny;
#: this is a safety valve, not a tuning knob)
_MAX_STATES = 20000


def _paths_reach_exit_stale(start: tuple, *, is_fresh, is_kill=None):
    """Core DFS: from (block, idx) just past the origin event, does some
    path cross an await (phase 1) and reach a non-raise exit without a
    `fresh` event after that first await?

    * is_fresh(event) — re-read/re-check that cleans the path once in
      phase 1 (exploration of that branch stops: later awaits are the
      fresh site's own problem).
    * is_kill(event) — invalidates the tracked value entirely (a re-def
      of the snapshot local); the path stops caring in ANY phase.

    Returns True if a stale path exists.
    """
    block, idx = start
    stack = [(block, idx, 0)]
    seen: set[tuple[int, int, int]] = set()
    states = 0
    while stack:
        b, i, phase = stack.pop()
        key = (id(b), i, phase)
        if key in seen:
            continue
        seen.add(key)
        states += 1
        if states > _MAX_STATES:
            return False  # degenerate CFG: stay silent, never hang
        events = b.events
        stopped = False
        while i < len(events):
            ev = events[i]
            kind = ev[0]
            if is_kill is not None and is_kill(ev):
                stopped = True
                break
            if kind == AWAIT and phase == 0:
                phase = 1
            elif phase == 1 and is_fresh(ev):
                stopped = True  # revalidated after the first await
                break
            elif kind == RAISE:
                stopped = True  # refusing to serve is not staleness
                break
            elif kind == RETURN:
                if phase == 1:
                    return True
                stopped = True
                break
            i += 1
        if stopped:
            continue
        if not b.succs:
            if phase == 1 and not b.terminated:
                return True  # fell off the end past an await
            continue
        for s in b.succs:
            stack.append((s, 0, phase))
    return False


def _event_positions(entry: Block):
    """(block, idx, event) for every event, blocks discovered once."""
    seen = {id(entry)}
    order = [entry]
    out = []
    qi = 0
    while qi < len(order):
        b = order[qi]
        qi += 1
        for i, ev in enumerate(b.events):
            out.append((b, i, ev))
        for s in list(b.succs) + list(b.exc_succs):
            if id(s) not in seen:
                seen.add(id(s))
                order.append(s)
    return out


def _reads_key(ev, keys) -> bool:
    return ev[0] == READ and any(keys_conflict(ev[1], k) for k in keys)


def _analyze_function(ctx: FileContext, info: cfg.FuncInfo) -> None:
    entry, shared = cfg.build_cfg(info, ctx.tree)
    positions = _event_positions(entry)
    reported: set[tuple[int, str]] = set()

    def report(node, rule_id, message):
        key = (getattr(node, "lineno", 0), rule_id)
        if key in reported:
            return
        reported.add(key)
        ctx.report(node, rule_id, message)

    for b, i, ev in positions:
        kind = ev[0]

        if kind == GUARD:
            _g, guard_kind, keys, node = ev
            stale = _paths_reach_exit_stale(
                (b, i + 1),
                is_fresh=lambda e, keys=keys: _reads_key(e, keys),
            )
            if stale:
                what = " / ".join(sorted(k[0] for k in keys))
                if guard_kind == "assert":
                    report(
                        node, R_GUARD,
                        f"{info.qualname}: assert on {what} is awaited "
                        "past without re-checking it after the wait",
                    )
                else:
                    report(
                        node, R_STALE,
                        f"{info.qualname}: guard on {what} validated "
                        "before an await but not re-read after it — all "
                        "state may change across a wait()",
                    )

        elif kind == DEF:
            _d, name, sources, node = ev
            if not sources:
                continue
            # a snapshot local: stale when a path crosses an await and
            # the snapshot then GOVERNS control flow (a test use) with
            # neither a re-def of the local nor a re-read of its source
            def fresh(e, name=name, sources=sources):
                return _reads_key(e, sources)

            def kill(e, name=name):
                return e[0] == DEF and e[1] == name

            # find a phase-1 test-use first (cheap pre-filter): without
            # one the def can't fire, whatever the paths do
            has_test_use = any(
                e[0] == USE and e[1] == name and e[2] and not e[4]
                for _b2, _i2, e in positions
            )
            if not has_test_use:
                continue
            if _paths_reach_test_use_stale(
                (b, i + 1), name, fresh, kill
            ):
                what = " / ".join(sorted(k[0] for k in sources))
                report(
                    node, R_STALE,
                    f"{info.qualname}: local {name!r} snapshots {what} "
                    "before an await and still guards behavior after "
                    "it without a re-read",
                )

        elif kind == WRITE:
            _w, wkey, uses, node = ev
            # taint shape: some def of a local in `uses` sourced from a
            # conflicting shared key, with an await between def and
            # write and no re-def in between → lost update
            for b2, i2, ev2 in positions:
                if ev2[0] != DEF or ev2[1] not in uses:
                    continue
                sources = ev2[2]
                if not any(keys_conflict(k, wkey) for k in sources):
                    continue
                name = ev2[1]
                if _paths_cross_await_to(
                    (b2, i2 + 1), target=(id(b), i),
                    kill=lambda e, name=name: e[0] == DEF and e[1] == name,
                ):
                    report(
                        node, R_RMW,
                        f"{info.qualname}: write to {wkey[0]} uses "
                        f"{name!r} read from it before an await — a "
                        "read-modify-write split across a yield point",
                    )

    # one-statement RMW: read k … await … write k inside a SINGLE
    # statement (`self.x = await f(self.x)`, `self.x += await f()`) —
    # the statement-boundary markers bound the scan
    for b, i, ev in positions:
        if ev[0] != READ or (len(ev) > 3 and ev[3]):
            continue  # weak receiver reads don't anchor an RMW
        rkey = ev[1]
        crossed = False
        for j in range(i + 1, len(b.events)):
            e2 = b.events[j]
            if e2[0] == STMT:
                break  # next statement: no longer "one statement"
            if e2[0] == AWAIT:
                crossed = True
            elif e2[0] == READ and keys_conflict(e2[1], rkey):
                break  # refreshed in-statement
            elif crossed and e2[0] == WRITE and keys_conflict(e2[1], rkey):
                report(
                    e2[3], R_RMW,
                    f"{info.qualname}: {rkey[0]} read, awaited past, "
                    "then written in one statement — the await races "
                    "the read-modify-write",
                )
                break

    # guard-not-rechecked: invariant-check calls
    for b, i, ev in positions:
        if ev[0] != CHECK:
            continue
        _c, dump, node = ev
        stale = _paths_reach_exit_stale(
            (b, i + 1),
            is_fresh=lambda e, dump=dump: e[0] == CHECK and e[1] == dump,
        )
        if stale:
            leaf = node.value.func
            leaf_name = (
                leaf.attr if isinstance(leaf, ast.Attribute) else
                getattr(leaf, "id", "check")
            )
            report(
                node, R_GUARD,
                f"{info.qualname}: {leaf_name}(...) validates a request "
                "before an await but is not repeated after it — the "
                "checked state may have changed across the wait",
            )


def _paths_reach_test_use_stale(start, name, fresh, kill) -> bool:
    """Snapshot-local variant of the stale DFS: stale when a path
    crosses its first await and then USES the local in a test (guard)
    position, with no source re-read (fresh) or local re-def (kill)
    since that await."""
    block, idx = start
    stack = [(block, idx, 0)]
    seen = set()
    states = 0
    while stack:
        b, i, phase = stack.pop()
        key = (id(b), i, phase)
        if key in seen:
            continue
        seen.add(key)
        states += 1
        if states > _MAX_STATES:
            return False
        stopped = False
        while i < len(b.events):
            ev = b.events[i]
            if kill(ev):
                stopped = True
                break
            if ev[0] == AWAIT and phase == 0:
                phase = 1
            elif phase == 1 and fresh(ev):
                stopped = True
                break
            elif (
                phase == 1 and ev[0] == USE and ev[1] == name
                and ev[2] and not ev[4]
            ):
                # `if snapshot or self.x > v:` — a re-read of the
                # source within the SAME statement (the test's own
                # tail) is the refresh idiom; scan to the statement
                # boundary before flagging
                refreshed = False
                for j in range(i + 1, len(b.events)):
                    e2 = b.events[j]
                    if e2[0] == STMT:
                        break
                    if fresh(e2):
                        refreshed = True
                        break
                if refreshed:
                    stopped = True
                    break
                return True
            elif ev[0] == RAISE:
                stopped = True
                break
            elif ev[0] == RETURN:
                stopped = True
                break
            i += 1
        if stopped:
            continue
        for s in b.succs:
            stack.append((s, 0, phase))
    return False


def _paths_cross_await_to(start, *, target, kill) -> bool:
    """Does a path from `start` reach the event at `target`
    (id(block), idx) having crossed >= 1 await, without `kill` firing?"""
    block, idx = start
    stack = [(block, idx, 0)]
    seen = set()
    states = 0
    while stack:
        b, i, phase = stack.pop()
        key = (id(b), i, phase)
        if key in seen:
            continue
        seen.add(key)
        states += 1
        if states > _MAX_STATES:
            return False
        stopped = False
        while i < len(b.events):
            if (id(b), i) == target:
                if phase == 1:
                    return True
                stopped = True  # reached it without an await: benign
                break
            ev = b.events[i]
            if kill(ev):
                stopped = True
                break
            if ev[0] == AWAIT:
                phase = 1
            elif ev[0] in (RAISE, RETURN):
                stopped = True
                break
            i += 1
        if stopped:
            continue
        for s in b.succs:
            stack.append((s, 0, phase))
    return False


@file_check
def check_flow(ctx: FileContext) -> None:
    """Run the flow family over every async def in a sim-scope file."""
    if not ctx.in_sim_scope:
        return
    for info in cfg.iter_async_functions(ctx.tree):
        _analyze_function(ctx, info)
