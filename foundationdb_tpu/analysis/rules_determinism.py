"""Determinism rules: the simulator's clock/entropy monopoly, enforced.

The whole value of the deterministic simulator (runtime/flow.py, the
Sim2 strategy) is that two runs from one seed are byte-identical — which
dies the moment actor code reads the wall clock, draws unseeded entropy,
or schedules through a loop the `Scheduler` doesn't own. The reference
gets this by construction (every actor compiles against flow's
`now()`/`deterministicRandom()`); here the linter enforces it.

Rules (sim scope only — see walker.SIM_SCOPE_PREFIXES):

* determinism.wall-clock — `time.time/monotonic/perf_counter/sleep/
  process_time`, `datetime.now/utcnow/today`. Use `sched.now()` /
  `sched.delay()`.
* determinism.unseeded-random — stdlib `random.*`, numpy's legacy
  global `numpy.random.<fn>` (anything but `default_rng`/`Generator`/
  `SeedSequence`), `os.urandom`, `uuid.uuid1/uuid4`, `secrets.*`. Use a
  seed-derived `numpy.random.default_rng` threaded in from the run.
* determinism.asyncio — importing or calling `asyncio` primitives:
  tasks scheduled there are invisible to the sim loop's (time,
  priority, seq) order, so seeds stop reproducing.
"""

from __future__ import annotations

import ast

from foundationdb_tpu.analysis.registry import file_check, rule
from foundationdb_tpu.analysis.walker import FileContext

R_WALL_CLOCK = rule(
    "determinism.wall-clock",
    "wall-clock read in sim-schedulable code; use Scheduler.now()/delay()",
)
R_UNSEEDED = rule(
    "determinism.unseeded-random",
    "unseeded entropy in sim-schedulable code; thread a seeded "
    "numpy.random.default_rng through instead",
)
R_ASYNCIO = rule(
    "determinism.asyncio",
    "raw asyncio primitive in sim-schedulable code; only the flow "
    "Scheduler may own task order",
)

_WALL_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.sleep",
    "time.process_time", "time.monotonic_ns", "time.time_ns",
    "time.perf_counter_ns",
}
_WALL_SUFFIXES = (
    "datetime.now", "datetime.utcnow", "datetime.today", "date.today",
)


def _is_wall_suffix(name: str) -> bool:
    """Dot-boundary suffix match: `datetime.datetime.now` yes,
    `start_datetime.now` no."""
    return any(
        name == s or name.endswith("." + s) for s in _WALL_SUFFIXES
    )
_ENTROPY_CALLS = {"os.urandom", "uuid.uuid1", "uuid.uuid4"}
_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence", "PCG64",
                 "Philox", "BitGenerator"}


@file_check
def check_determinism(ctx: FileContext) -> None:
    if not ctx.in_sim_scope:
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "asyncio" or a.name.startswith("asyncio."):
                    ctx.report(node, R_ASYNCIO, "import asyncio")
        elif isinstance(node, ast.ImportFrom):
            if node.module and (
                node.module == "asyncio"
                or node.module.startswith("asyncio.")
            ):
                ctx.report(node, R_ASYNCIO, f"from {node.module} import ...")
        elif isinstance(node, ast.Call):
            name = ctx.resolved(node.func)
            if name is None:
                continue
            if name in _WALL_CALLS or _is_wall_suffix(name):
                ctx.report(node, R_WALL_CLOCK, f"call to {name}()")
            elif name in _ENTROPY_CALLS or name.startswith("secrets."):
                ctx.report(node, R_UNSEEDED, f"call to {name}()")
            elif name.startswith("random."):
                ctx.report(
                    node, R_UNSEEDED,
                    f"call to stdlib {name}() (module-level RNG)",
                )
            elif name.startswith("numpy.random."):
                leaf = name.rsplit(".", 1)[1]
                if leaf not in _NP_RANDOM_OK:
                    ctx.report(
                        node, R_UNSEEDED,
                        f"call to {name}() (legacy global numpy RNG)",
                    )
            elif name.startswith("asyncio."):
                ctx.report(node, R_ASYNCIO, f"call to {name}()")
