"""The AST-extracted wire-protocol registry: frames, tokens, codecs.

The reference's protocol surface is machine-readable by construction —
typed FlowTransport endpoints with FileIdentifiers, WellKnownEndpoints.h
tokens, `serializer(ar, f1, f2, ...)` field lists the flatbuffers pass
walks (fdbrpc/fdbrpc.h, flow/flat_buffers.h). This framework's wire
layer is hand-rolled Python, so the equivalent inventory is extracted
here, statically, from the source of `wire/codec.py`,
`wire/transport.py`, and `cluster/multiprocess.py`:

* every frame id registered with `codec.register(...)` — both the
  declarative `_message(id, "Name", [fields])` frames and the
  hand-written encode/decode pairs,
* every `TOKEN_*` RPC endpoint constant,
* every `server.register(TOKEN_X, handler)` dispatch binding,
* every client-side `conn.call(TOKEN_X, ...)` site (with its timeout
  and error-classification posture),
* the ordered primitive-op stream of each hand-written encoder and
  decoder (the field-drift comparison surface), and
* which frames carry a generation `epoch` (the fencing contract).

One extraction, three consumers (one copy or they drift): the `wire.*`
flowcheck family (`rules_wire.py`), the checked-in
`analysis/wire_manifest.json`, and the structure-aware codec fuzzer
(`scripts/wire_fuzz.py`) — the fuzzer mutates exactly the frames the
static pass accounts for.

stdlib-`ast` only, like the rest of flowcheck: nothing here imports the
scanned modules.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

#: primitive codec ops (codec.w_*/r_* suffixes the stream extractor
#: treats as leaves rather than helper calls)
PRIM_KINDS = {
    "u8", "u16", "u32", "i64", "u64", "bytes", "str", "bool", "mutation",
}

#: wire-layout expansion to fixed primitives, for comparing an encoder
#: stream against its paired decoder even when one side hand-rolls a
#: composite (e.g. r_resolve_columnar reads a u32 length + raw slice
#: where the encoder called w_bytes)
_EXPAND = {
    "u8": ("u8",),
    "u16": ("u16",),
    "u32": ("u32",),
    "i64": ("i64",),
    "u64": ("u64",),
    "bool": ("u8",),
    "bytes": ("u32", "raw"),
    "str": ("u32", "raw"),
    "mutation": ("u8", "u32", "raw", "u32", "raw"),
    "raw": ("raw",),
}

#: except-clause types that count as classifying a wire RPC's failure
#: (wire.unclassified-error): the transport taxonomy, the asyncio/OS
#: errors a call can surface, and the broad catches control-plane
#: callers use deliberately. CancelledError alone is NOT classification.
CLASSIFIER_LEAVES = {
    "RemoteError", "TransportError", "ChecksumError", "HandshakeError",
    "UnknownEndpointError", "ConnectionError", "OSError", "IOError",
    "TimeoutError", "Exception", "BaseException",
}


@dataclasses.dataclass(frozen=True)
class TokenDecl:
    name: str
    value: int
    path: str
    node: ast.AST


@dataclasses.dataclass(frozen=True)
class FrameDecl:
    type_id: int
    name: str
    #: "message" (declarative `_message` frame) or "handwritten"
    #: (explicit codec.register with named encode/decode functions)
    style: str
    path: str
    node: ast.AST
    #: (field, kind) pairs for "message" frames; None for handwritten
    fields: tuple | None = None
    encoder: str | None = None
    decoder: str | None = None


@dataclasses.dataclass(frozen=True)
class HandlerReg:
    token: str          # TOKEN_* constant name at the register site
    handler: str | None  # method/function name the token dispatches to
    path: str
    node: ast.AST


@dataclasses.dataclass(frozen=True)
class HandlerDef:
    cls: str | None     # enclosing class name, None for module functions
    method: str
    frame: str          # the request parameter's annotated frame type
    path: str
    node: ast.AST       # the AsyncFunctionDef


@dataclasses.dataclass(frozen=True)
class CallSite:
    token: str          # TOKEN_* leaf, or "token" for forwarding wrappers
    has_timeout: bool   # an explicit timeout= keyword (not None)
    classified: bool    # lexically covered by a classifying except clause
    path: str
    node: ast.AST


@dataclasses.dataclass
class WireFacts:
    """Everything the wire pass needs from ONE module's AST — computed
    once per file and memoized on the FileContext, so the flowcheck
    tree check, the manifest writer, and the fuzzer's registry build
    all share the same walk."""

    path: str
    tokens: list = dataclasses.field(default_factory=list)
    frames: list = dataclasses.field(default_factory=list)
    handler_regs: list = dataclasses.field(default_factory=list)
    handler_defs: list = dataclasses.field(default_factory=list)
    call_sites: list = dataclasses.field(default_factory=list)
    #: name -> FunctionDef for every w_*/r_*/_w_*/_r_* codec function
    codec_funcs: dict = dataclasses.field(default_factory=dict)
    protocol_version: int | None = None


def _leaf(node: ast.AST) -> str | None:
    """Last segment of a Name/attribute chain: `mp.TOKEN_X` -> TOKEN_X."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _const_int(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _annotation_name(node: ast.AST | None) -> str | None:
    """Frame type named by a parameter annotation — `TLogPush`,
    `"TLogPop"` (string annotation), or `mp.StatusRequest`."""
    if node is None:
        return None
    s = _const_str(node)
    if s is not None:
        return s.rsplit(".", 1)[-1]
    leaf = _leaf(node)
    return leaf


def _classifying(handlers: list) -> bool:
    for h in handlers:
        if h.type is None:  # bare except
            return True
        types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for t in types:
            if _leaf(t) in CLASSIFIER_LEAVES:
                return True
    return False


def _is_wire_call(node: ast.AST) -> tuple[str, bool] | None:
    """(token_leaf, has_explicit_timeout) when `node` is a wire RPC
    call: `<conn>.call(TOKEN_X, ...)` or a forwarding wrapper's
    `<conn>.call(token, ...)`."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "call"
            and node.args):
        return None
    tok = _leaf(node.args[0])
    if tok is None or not (tok.startswith("TOKEN_") or tok == "token"):
        return None
    has_timeout = any(
        k.arg == "timeout"
        and not (isinstance(k.value, ast.Constant) and k.value.value is None)
        for k in node.keywords
    )
    return tok, has_timeout


def _scan_calls(node: ast.AST, covered: bool, path: str, out: list) -> None:
    """Collect wire call sites with their lexical try/except coverage.
    `covered` is true inside a try body whose handlers include a
    classifying exception type; function boundaries reset it (errors do
    not propagate lexically across a nested def)."""
    if isinstance(node, ast.Try):
        inner = covered or _classifying(node.handlers)
        for n in node.body:
            _scan_calls(n, inner, path, out)
        for h in node.handlers:
            for n in h.body:
                _scan_calls(n, covered, path, out)
        for n in list(node.orelse) + list(node.finalbody):
            _scan_calls(n, covered, path, out)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        body = node.body if not isinstance(node, ast.Lambda) else [node.body]
        for n in body:
            _scan_calls(n, False, path, out)
        return
    hit = _is_wire_call(node)
    if hit is not None:
        tok, has_timeout = hit
        out.append(CallSite(
            token=tok, has_timeout=has_timeout, classified=covered,
            path=path, node=node,
        ))
    for n in ast.iter_child_nodes(node):
        _scan_calls(n, covered, path, out)


def file_facts(tree: ast.Module, path: str) -> WireFacts:
    """Extract one module's wire facts. Pure: AST in, facts out."""
    facts = WireFacts(path=path)

    # module-level constants: TOKEN_* table and PROTOCOL_VERSION
    for stmt in tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            continue
        name = stmt.targets[0].id
        value = _const_int(stmt.value)
        if value is None:
            continue
        if name.startswith("TOKEN_"):
            facts.tokens.append(TokenDecl(name, value, path, stmt))
        elif name == "PROTOCOL_VERSION":
            facts.protocol_version = value

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fname = node.name
            if fname.startswith(("w_", "r_", "_w_", "_r_")):
                facts.codec_funcs[fname] = node
            if isinstance(node, ast.AsyncFunctionDef) and node.args.args:
                args = node.args.args
                req = args[1] if args[0].arg == "self" and len(args) > 1 \
                    else args[0]
                frame = _annotation_name(req.annotation)
                if frame:
                    facts.handler_defs.append(HandlerDef(
                        cls=None, method=fname, frame=frame,
                        path=path, node=node,
                    ))
            continue
        if not isinstance(node, ast.Call):
            continue
        leaf = _leaf(node.func)
        if leaf == "_message" and len(node.args) >= 3:
            type_id = _const_int(node.args[0])
            name = _const_str(node.args[1])
            fields_node = node.args[2]
            if type_id is None or name is None \
                    or not isinstance(fields_node, ast.List):
                continue
            fields = []
            for elt in fields_node.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) >= 2:
                    f, k = _const_str(elt.elts[0]), _const_str(elt.elts[1])
                    if f is not None and k is not None:
                        fields.append((f, k))
            facts.frames.append(FrameDecl(
                type_id=type_id, name=name, style="message", path=path,
                node=node, fields=tuple(fields),
            ))
        elif leaf == "register" and len(node.args) == 4 \
                and _const_int(node.args[0]) is not None:
            facts.frames.append(FrameDecl(
                type_id=_const_int(node.args[0]),
                name=_leaf(node.args[1]) or "?",
                style="handwritten", path=path, node=node,
                encoder=_leaf(node.args[2]), decoder=_leaf(node.args[3]),
            ))
        elif leaf == "register" and len(node.args) == 2:
            tok = _leaf(node.args[0])
            if tok is None or not tok.startswith("TOKEN_"):
                continue
            h = node.args[1]
            handler: str | None = None
            if isinstance(h, ast.Name):
                handler = h.id
            elif isinstance(h, ast.Attribute):
                handler = h.attr
            elif isinstance(h, ast.Call) and _leaf(h.func) == "route" \
                    and len(h.args) == 2:
                handler = _const_str(h.args[1])
            facts.handler_regs.append(HandlerReg(
                token=tok, handler=handler, path=path, node=node,
            ))

    # attach class names to handler defs (the annotation walk above sees
    # methods without their enclosing class)
    cls_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls_of[id(item)] = node.name
    facts.handler_defs = [
        dataclasses.replace(hd, cls=cls_of.get(id(hd.node)))
        for hd in facts.handler_defs
    ]

    _scan_calls(tree, False, path, facts.call_sites)
    return facts


def facts_of(ctx) -> WireFacts:
    """Per-FileContext memoized facts: the flowcheck run computes each
    module's facts at most once no matter how many wire rules ask."""
    cached = getattr(ctx, "_wire_facts", None)
    if cached is None:
        cached = file_facts(ctx.tree, ctx.path)
        ctx._wire_facts = cached
    return cached


# ---------------------------------------------------------------------------
# Encoder/decoder op-stream extraction (wire.codec-field-drift).


def _loop_tag(iter_node: ast.AST) -> str:
    """Loops over COLUMNAR_LAYOUT pair up across enc/dec by construction
    (both sides iterate the ONE pinned layout constant)."""
    for sub in ast.walk(iter_node):
        if isinstance(sub, ast.Name) and sub.id == "COLUMNAR_LAYOUT":
            return "layout"
    return "loop"


def _branch_ops(stmts: list, extractor) -> tuple:
    ops = extractor(stmts)
    return tuple(ops)


def encoder_ops(fn: ast.FunctionDef) -> list:
    """Ordered (unexpanded) op stream of a hand-written encoder: w_KIND
    calls become KIND, helper calls become ("call", suffix), put_raw
    becomes "raw", loops nest."""

    def walk(stmts: list) -> list:
        ops: list = []
        for s in stmts:
            if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
                leaf = _leaf(s.value.func)
                if leaf == "put_raw":
                    ops.append("raw")
                elif leaf and leaf.lstrip("_").startswith("w_"):
                    kind = leaf.lstrip("_")[2:]
                    ops.append(kind if kind in PRIM_KINDS
                               else ("call", kind))
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                ops.append((_loop_tag(s.iter), _branch_ops(s.body, walk)))
            elif isinstance(s, ast.If):
                body, orelse = walk(s.body), walk(s.orelse)
                if body or orelse:
                    ops.append(("cond", tuple(body), tuple(orelse)))
        return ops

    return walk(fn.body)


def decoder_ops(fn: ast.FunctionDef) -> list:
    """Ordered (unexpanded) op stream of a hand-written decoder: r_KIND
    reads become KIND, helper reads ("call", suffix), np.frombuffer and
    manual buf[off:off+n] slices become "raw". Validation-only branches
    (raise CodecError) are transparent — raises reject, they don't read."""

    def value_ops(v: ast.AST) -> list:
        if isinstance(v, ast.Call):
            leaf = _leaf(v.func)
            if leaf == "frombuffer":
                return ["raw"]
            if leaf and leaf.lstrip("_").startswith("r_"):
                kind = leaf.lstrip("_")[2:]
                return [kind if kind in PRIM_KINDS else ("call", kind)]
        elif isinstance(v, ast.Subscript) and isinstance(v.slice, ast.Slice):
            return ["raw"]
        return []

    def walk(stmts: list) -> list:
        ops: list = []
        for s in stmts:
            if isinstance(s, ast.Assign):
                ops.extend(value_ops(s.value))
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                ops.append((_loop_tag(s.iter), _branch_ops(s.body, walk)))
            elif isinstance(s, ast.If):
                body, orelse = walk(s.body), walk(s.orelse)
                if body or orelse:
                    ops.append(("cond", tuple(body), tuple(orelse)))
        return ops

    return walk(fn.body)


def expand_ops(ops: list, funcs: dict, side: str, _depth: int = 0) -> list:
    """Expand an op stream to fixed primitives + loop structure so an
    encoder and decoder compare even when their helper granularity
    differs (w_bytes vs r_u32 + raw slice). `side` picks which helper
    family ("w" or "r") resolves ("call", name) ops."""
    if _depth > 8:  # codec helpers don't recurse; bound it anyway
        return [("opaque", "depth")]
    out: list = []
    for op in ops:
        if isinstance(op, str):
            out.extend(_EXPAND.get(op, (op,)))
        elif op[0] == "call":
            fn = funcs.get(f"{side}_{op[1]}") or funcs.get(f"_{side}_{op[1]}")
            if fn is None:
                out.append(("opaque", op[1]))
            else:
                sub = encoder_ops(fn) if side == "w" else decoder_ops(fn)
                out.extend(expand_ops(sub, funcs, side, _depth + 1))
        elif op[0] in ("loop", "layout"):
            out.append((op[0],
                        tuple(expand_ops(list(op[1]), funcs, side,
                                         _depth + 1))))
        elif op[0] == "cond":
            out.append(("cond",
                        tuple(expand_ops(list(op[1]), funcs, side,
                                         _depth + 1)),
                        tuple(expand_ops(list(op[2]), funcs, side,
                                         _depth + 1))))
    return out


def ops_signature(ops: list) -> str:
    """Human-readable serialization of an (unexpanded) op stream — the
    manifest's layout string for hand-written frames."""
    parts = []
    for op in ops:
        if isinstance(op, str):
            parts.append(op)
        elif op[0] == "call":
            parts.append(op[1])
        elif op[0] in ("loop", "layout"):
            parts.append(f"{op[0]}[{ops_signature(list(op[1]))}]")
        elif op[0] == "cond":
            parts.append(
                f"cond[{ops_signature(list(op[1]))}"
                f"/{ops_signature(list(op[2]))}]"
            )
    return " ".join(parts)


def encoder_fields(fn: ast.FunctionDef) -> set[str]:
    """Field names the encoder reads off its message parameter."""
    if len(fn.args.args) < 2:
        return set()
    msg = fn.args.args[1].arg
    return {
        node.attr for node in ast.walk(fn)
        if isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name) and node.value.id == msg
    }


def decoder_fields(fn: ast.FunctionDef) -> set[str]:
    """Field names the decoder's constructed message receives (the
    keywords of the returned `(Cls(...), off)` call)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Tuple) \
                and node.value.elts \
                and isinstance(node.value.elts[0], ast.Call):
            return {k.arg for k in node.value.elts[0].keywords if k.arg}
    return set()


# ---------------------------------------------------------------------------
# Tree-level aggregation.


@dataclasses.dataclass
class WireRegistry:
    tokens: list
    frames: list
    handler_regs: list
    handler_defs: list
    call_sites: list
    codec_funcs: dict            # name -> (path, FunctionDef)
    protocol_version: int | None

    def epoch_frames(self) -> set[str]:
        """Frames carrying a generation epoch: a declared `epoch` field,
        or a hand-written encoder that writes `msg.epoch`."""
        out = set()
        for f in self.frames:
            if f.style == "message":
                if any(name == "epoch" for name, _k in f.fields or ()):
                    out.add(f.name)
            elif f.encoder:
                entry = self.codec_funcs.get(f.encoder)
                if entry and "epoch" in encoder_fields(entry[1]):
                    out.add(f.name)
        return out

    def manifest(self) -> dict:
        """The checked-in wire_manifest.json payload: protocol version,
        frame id -> name + layout, token name -> id."""
        frames: dict[str, dict] = {}
        for f in sorted(self.frames, key=lambda f: f.type_id):
            if f.style == "message":
                layout = " ".join(f"{n}:{k}" for n, k in f.fields or ())
            else:
                entry = self.codec_funcs.get(f.encoder or "")
                layout = ops_signature(encoder_ops(entry[1])) if entry \
                    else "?"
            frames[f"0x{f.type_id:04x}"] = {"name": f.name, "layout": layout}
        tokens = {
            t.name: f"0x{t.value:04x}"
            for t in sorted(self.tokens, key=lambda t: (t.name, t.value))
        }
        pv = None if self.protocol_version is None \
            else f"0x{self.protocol_version:012x}"
        return {"protocol_version": pv, "frames": frames, "tokens": tokens}


def aggregate(all_facts: list[WireFacts]) -> WireRegistry:
    reg = WireRegistry(
        tokens=[], frames=[], handler_regs=[], handler_defs=[],
        call_sites=[], codec_funcs={}, protocol_version=None,
    )
    for facts in all_facts:
        reg.tokens.extend(facts.tokens)
        reg.frames.extend(facts.frames)
        reg.handler_regs.extend(facts.handler_regs)
        reg.handler_defs.extend(facts.handler_defs)
        reg.call_sites.extend(facts.call_sites)
        for name, fn in facts.codec_funcs.items():
            reg.codec_funcs.setdefault(name, (facts.path, fn))
        if facts.protocol_version is not None:
            reg.protocol_version = facts.protocol_version
    return reg


def load_repo_registry(root: Path | None = None) -> WireRegistry:
    """Standalone entry point (scripts/wire_fuzz.py): parse the package
    and aggregate — the SAME extraction the flowcheck gate runs, without
    importing any scanned module."""
    from foundationdb_tpu.analysis import walker

    root = root or Path(__file__).resolve().parents[2]
    all_facts = []
    for path in walker.discover(root):
        rel = path.relative_to(root).as_posix()
        if rel.startswith("foundationdb_tpu/analysis/"):
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"), filename=rel)
        except SyntaxError:
            continue
        all_facts.append(file_facts(tree, rel))
    return aggregate(all_facts)
