"""Run the full analysis and render findings: the flowcheck driver.

`run_analysis(root)` is the one entry point everything shares — the CLI
(`__main__.py`), the self-check test (`tests/test_flowcheck.py`), and
`scripts/check.sh`. Findings render as `path:line [rule] message`, one
per line, stable enough to grep and to click in an editor.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from collections import Counter
from pathlib import Path

from foundationdb_tpu.analysis import baseline as baseline_mod
from foundationdb_tpu.analysis import registry, walker
from foundationdb_tpu.analysis.walker import FileContext, Finding, _matches

R_STALE_IGNORE = registry.rule(
    "flowcheck.stale-ignore",
    "a '# flowcheck: ignore[...]' comment that suppresses nothing — "
    "dead ignores must not accumulate",
)


@dataclasses.dataclass
class AnalysisResult:
    contexts: list[FileContext]
    findings: list[Finding]      # every unsuppressed finding in the tree
    new: list[Finding]           # beyond the baseline: these fail the gate
    baselined: list[Finding]
    stale: Counter               # baseline entries nothing matched (fixed)
    suppressed: int              # findings absorbed by ignore[] comments
    #: rule family -> wall seconds (plus "parse"), for --timings: cost
    #: regressions in the static pass stay visible, not discovered by feel
    timings: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.new


def analyze_source(source: str, path: str = "foundationdb_tpu/cluster/_snippet.py") -> list[Finding]:
    """Lint one source string as if it lived at `path` (fixture entry
    point for tests: the path picks the scope rules apply under)."""
    registry.load_rules()
    ctx = FileContext(path, source)
    for check in registry.FILE_CHECKS:
        check(ctx)
    return sorted(ctx.findings, key=lambda f: (f.line, f.rule))


def run_analysis(
    root: Path | None = None,
    baseline_path: Path | None = None,
    manifest_path: Path | None = None,
    use_baseline: bool = True,
) -> AnalysisResult:
    registry.load_rules()
    root = (root or Path(__file__).resolve().parents[2])
    ctxs: list[FileContext] = []
    findings: list[Finding] = []
    timings: dict[str, float] = {}

    def _family(fn) -> str:
        leaf = fn.__module__.rsplit(".", 1)[-1]
        return leaf[len("rules_"):] if leaf.startswith("rules_") else leaf

    for path in walker.discover(root):
        t0 = time.perf_counter()
        try:
            ctx = walker.parse_file(root, path)
        except SyntaxError as e:
            # a file the interpreter would reject: surface, don't crash
            findings.append(Finding(
                path=path.relative_to(root).as_posix(),
                line=e.lineno or 1,
                rule="flowcheck.parse-error",
                message=str(e.msg),
            ))
            continue
        finally:
            timings["parse"] = (
                timings.get("parse", 0.0) + time.perf_counter() - t0
            )
        ctxs.append(ctx)
        for check in registry.FILE_CHECKS:
            t0 = time.perf_counter()
            check(ctx)
            fam = _family(check)
            timings[fam] = (
                timings.get(fam, 0.0) + time.perf_counter() - t0
            )
        findings.extend(ctx.findings)
    for tree_rule in registry.TREE_CHECKS:
        t0 = time.perf_counter()
        findings.extend(tree_rule(ctxs, manifest_path=manifest_path))
        fam = _family(tree_rule)
        timings[fam] = timings.get(fam, 0.0) + time.perf_counter() - t0

    # the stale-suppression audit: after EVERY rule has run, an
    # ignore[] pattern that absorbed no finding is dead weight — the
    # violation it justified was fixed (or never existed), and leaving
    # the marker would silently blind the gate to a future regression
    # on that line. Not suppressible by construction (suppressing a
    # stale ignore with another ignore is turtles all the way down).
    for ctx in ctxs:
        for line, pats in sorted(ctx.suppressions.items()):
            absorbed = [f for f in ctx.suppressed if f.line == line]
            for pat in sorted(pats):
                if any(_matches(f.rule, pat) for f in absorbed):
                    continue
                marker = (
                    "# flowcheck: ignore" if pat == "*"
                    else f"# flowcheck: ignore[{pat}]"
                )
                findings.append(Finding(
                    path=ctx.path, line=line, rule=R_STALE_IGNORE,
                    message=(
                        f"{marker!r} suppresses nothing here — remove "
                        "the dead ignore"
                    ),
                ))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    allowed = (
        baseline_mod.load_baseline(baseline_path) if use_baseline
        else Counter()
    )
    # stale-ignore findings never enter baseline matching: a
    # --write-baseline run must not freeze a dead ignore into
    # permanence (the accumulation this rule exists to prevent)
    baselineable = [f for f in findings if f.rule != R_STALE_IGNORE]
    new, baselined, stale = baseline_mod.split_findings(
        baselineable, allowed
    )
    new.extend(f for f in findings if f.rule == R_STALE_IGNORE)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return AnalysisResult(
        contexts=ctxs,
        findings=findings,
        new=new,
        baselined=baselined,
        stale=stale,
        suppressed=sum(len(c.suppressed) for c in ctxs),
        timings=timings,
    )


def render(result: AnalysisResult, *, show_all: bool = False,
           out=None) -> None:
    out = out or sys.stdout
    shown = result.findings if show_all else result.new
    for f in shown:
        tag = ""
        if show_all and f in result.baselined:
            tag = "  (baselined)"
        print(f.render() + tag, file=out)
    print(
        f"flowcheck: {len(result.findings)} finding(s) — "
        f"{len(result.new)} new, {len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed",
        file=out,
    )
    if result.stale:
        n = sum(result.stale.values())
        print(
            f"flowcheck: {n} baseline entr{'y' if n == 1 else 'ies'} no "
            "longer match (fixed?) — run --write-baseline to shrink the "
            "baseline",
            file=out,
        )


def render_timings(result: AnalysisResult, out=None) -> None:
    """Per-family wall-time breakdown (--timings): slowest first, so a
    rule family that regresses the gate's cost names itself."""
    out = out or sys.stdout
    total = sum(result.timings.values())
    for fam, secs in sorted(
        result.timings.items(), key=lambda kv: -kv[1]
    ):
        print(f"flowcheck timing: {fam:12s} {secs * 1000:7.1f}ms",
              file=out)
    print(f"flowcheck timing: {'total':12s} {total * 1000:7.1f}ms",
          file=out)
