"""`python -m foundationdb_tpu.analysis` — the flowcheck gate CLI.

Exit codes: 0 = no new violations (baselined findings don't fail),
1 = new violations, 2 = bad invocation. `scripts/check.sh` runs this
before pytest; CI treats nonzero as a failed build.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from foundationdb_tpu.analysis import baseline as baseline_mod
from foundationdb_tpu.analysis import manifest as manifest_mod
from foundationdb_tpu.analysis import registry
from foundationdb_tpu.analysis.report import (
    render,
    render_timings,
    run_analysis,
)
from foundationdb_tpu.analysis.rules_probes import tree_manifest
from foundationdb_tpu.analysis.rules_trace import tree_trace_manifest
from foundationdb_tpu.analysis.rules_wire import tree_wire_manifest


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.analysis",
        description=(
            "flowcheck: determinism / actor-safety / JAX-hazard / "
            "probe-accounting lint gate"
        ),
    )
    ap.add_argument(
        "--root", type=Path, default=None,
        help="repo root (default: derived from the package location)",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="show baselined findings too, not just new ones",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="treat every finding as new (full-tree view, exit 1 if any)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="freeze the current findings as the new baseline",
    )
    ap.add_argument(
        "--write-manifest", action="store_true",
        help="regenerate analysis/probe_manifest.json from the tree",
    )
    ap.add_argument(
        "--write-trace-manifest", action="store_true",
        help="regenerate analysis/trace_manifest.json from the tree",
    )
    ap.add_argument(
        "--write-wire-manifest", action="store_true",
        help="regenerate analysis/wire_manifest.json from the tree",
    )
    ap.add_argument(
        "--timings", action="store_true",
        help="print the per-rule-family wall-time breakdown",
    )
    ap.add_argument(
        "--rules", action="store_true", help="print the rule catalog",
    )
    args = ap.parse_args(argv)

    if args.rules:
        registry.load_rules()
        for r in sorted(registry.RULES.values(), key=lambda r: r.id):
            print(f"{r.id:26s} {r.doc}")
        return 0

    result = run_analysis(
        root=args.root, use_baseline=not args.no_baseline
    )

    if args.write_manifest:
        manifest_mod.save_manifest(tree_manifest(result.contexts))
        print(f"wrote {manifest_mod.manifest_path()}")
        # manifest drift findings are now stale: re-run for a clean view
        result = run_analysis(
            root=args.root, use_baseline=not args.no_baseline
        )
    if args.write_trace_manifest:
        manifest_mod.save_trace_manifest(
            tree_trace_manifest(result.contexts)
        )
        print(f"wrote {manifest_mod.trace_manifest_path()}")
        result = run_analysis(
            root=args.root, use_baseline=not args.no_baseline
        )
    if args.write_wire_manifest:
        manifest_mod.save_wire_manifest(
            tree_wire_manifest(result.contexts)
        )
        print(f"wrote {manifest_mod.wire_manifest_path()}")
        result = run_analysis(
            root=args.root, use_baseline=not args.no_baseline
        )
    if args.write_baseline:
        baseline_mod.save_baseline(result.findings)
        print(
            f"wrote {baseline_mod.baseline_path()} "
            f"({len(result.findings)} entries)"
        )
        return 0

    render(result, show_all=args.all)
    if args.timings:
        render_timings(result)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
