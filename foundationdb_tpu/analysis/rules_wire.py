"""The wire-protocol contract pass: `wire.*`.

PR 13 made three properties load-bearing for the wire cluster — epoch
fencing on txn-path handlers, token-dispatched RPC, and
CodecError-never-crash decoding — but only by convention. The reference
gets the equivalent guarantees from its build system: FlowTransport
endpoints are typed, FileIdentifiers are unique by a compile step, and
`serializer(ar, ...)` makes encode/decode one declaration
(fdbrpc/fdbrpc.h, flow/flat_buffers.h). These tree rules re-create that
hostility to silent protocol drift over the hand-rolled Python wire
layer, driven by the AST-extracted registry in `wire_registry.py` (the
same registry `scripts/wire_fuzz.py` mutates at runtime):

* wire.token-collision — two frames registered on one type id, or two
  TOKEN_* endpoints on one value: dispatch becomes ambiguous the day it
  happens, loudly here instead.
* wire.codec-field-drift — a hand-written encode/decode pair whose
  primitive op streams diverge, or whose field sets differ (encoder
  writes a field the decoder never reconstructs): the classic
  silent-corruption bug `serializer(...)` makes impossible.
* wire.epoch-unfenced-handler — a registered handler for an
  epoch-carrying frame that awaits or mutates role state before the
  stale_epoch fence: a stale-generation message could act on a
  recovered role.
* wire.call-without-timeout — an RPC call site with no explicit bound:
  one dead peer wedges the caller forever.
* wire.unclassified-error — an RPC call site whose failures no
  enclosing except clause classifies retryable-or-not; an escaping raw
  transport error skips the caller's retry/fail-safe policy. Sites
  whose classification boundary is a caller one frame up carry a
  justified `# flowcheck: ignore[wire.unclassified-error]` naming it.
* wire.manifest-drift — `analysis/wire_manifest.json` out of date with
  the tree; changing the message set without bumping PROTOCOL_VERSION
  is called out specifically (mixed-version peers would disagree about
  frame layouts while handshaking identically).
"""

from __future__ import annotations

import ast
from pathlib import Path

from foundationdb_tpu.analysis import manifest as manifest_mod
from foundationdb_tpu.analysis import wire_registry as wr
from foundationdb_tpu.analysis.registry import rule, tree_check
from foundationdb_tpu.analysis.walker import FileContext, Finding

R_COLLISION = rule(
    "wire.token-collision",
    "two frames share a type id, or two TOKEN_* endpoints share a value",
)
R_DRIFT_CODEC = rule(
    "wire.codec-field-drift",
    "hand-written encode/decode pair out of sync (op stream or field set)",
)
R_UNFENCED = rule(
    "wire.epoch-unfenced-handler",
    "handler for an epoch-carrying frame awaits/mutates state before "
    "the stale_epoch fence",
)
R_NO_TIMEOUT = rule(
    "wire.call-without-timeout",
    "RPC call site without an explicit timeout bound",
)
R_UNCLASSIFIED = rule(
    "wire.unclassified-error",
    "RPC call site whose errors no enclosing except classifies",
)
R_DRIFT_MANIFEST = rule(
    "wire.manifest-drift",
    "wire_manifest.json does not match the tree (--write-wire-manifest; "
    "message-set changes must bump PROTOCOL_VERSION)",
)

#: mutating container/dict methods: `self.x.append(...)` before the
#: fence is role-state mutation even though no attribute is assigned
MUTATOR_METHODS = {
    "append", "add", "update", "extend", "insert", "remove", "discard",
    "clear", "pop", "popleft", "setdefault", "appendleft",
}


def wire_contexts(ctxs: list[FileContext]) -> list[FileContext]:
    """THE exclusion policy for the wire pass, shared by the tree check,
    --write-wire-manifest, and (via the same discovery rule in
    wire_registry.load_repo_registry) the fuzzer: skip this package —
    rule docs and the extractor itself mention the scanned callables."""
    return [c for c in ctxs if not c.rel.startswith("analysis/")]


# ---------------------------------------------------------------------------
# wire.epoch-unfenced-handler: the fence-precedes-effects path scan.


def _is_fence(stmt: ast.stmt) -> bool:
    """The two fence idioms: a `_fence_epoch(req, role)` call statement,
    or the inline `if req.epoch < self.epoch: ... raise` compare."""
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        leaf = wr._leaf(stmt.value.func)
        if leaf and leaf.endswith("fence_epoch"):
            return True
    if isinstance(stmt, ast.If):
        tests_epoch = any(
            isinstance(n, ast.Attribute) and n.attr == "epoch"
            for n in ast.walk(stmt.test)
        )
        raises = any(
            isinstance(n, ast.Raise)
            for s in stmt.body for n in ast.walk(s)
        )
        return tests_epoch and raises
    return False


def _stmt_effect(stmt: ast.stmt) -> ast.AST | None:
    """First await or self-state mutation anywhere inside `stmt` (the
    full compound statement — a fence nested past an effect can't save
    it), or None. Local work (assigns to locals, pure calls, trace
    emits) passes through."""
    for n in ast.walk(stmt):
        if isinstance(n, ast.Await):
            return n
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    return n
                if isinstance(t, ast.Subscript):
                    base = t.value
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute) \
                            and isinstance(base.value, ast.Name) \
                            and base.value.id == "self":
                        return n
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in MUTATOR_METHODS:
            base = n.func.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                return n
    return None


def unfenced_effect(handler: ast.AsyncFunctionDef) -> ast.AST | None:
    """The first await/state-mutation a stale-epoch message would reach,
    if it comes before any fence; None when the handler fences first."""
    for stmt in handler.body:
        if _is_fence(stmt):
            return None
        effect = _stmt_effect(stmt)
        if effect is not None:
            return effect
    return None


# ---------------------------------------------------------------------------
# wire.manifest-drift: diff rendering.


def _manifest_diff(stored: dict, cur: dict) -> str:
    parts = []
    for key in ("frames", "tokens"):
        s, c = stored.get(key, {}), cur.get(key, {})
        added = sorted(set(c) - set(s))
        removed = sorted(set(s) - set(c))
        changed = sorted(k for k in set(c) & set(s) if c[k] != s[k])
        if added:
            parts.append(f"new {key}: {added[:4]}")
        if removed:
            parts.append(f"removed {key}: {removed[:4]}")
        if changed:
            parts.append(f"changed {key}: {changed[:4]}")
    if stored.get("protocol_version") != cur.get("protocol_version"):
        parts.append(
            f"protocol_version {stored.get('protocol_version')} -> "
            f"{cur.get('protocol_version')}"
        )
    return "; ".join(parts) or "layout detail changed"


@tree_check
def check_wire(ctxs: list[FileContext],
               manifest_path: Path | None = None) -> list[Finding]:
    findings: list[Finding] = []
    by_path = {c.path: c for c in ctxs}

    def report(path: str, node: ast.AST, rule_id: str,
               message: str) -> None:
        ctx = by_path.get(path)
        if ctx is None:
            return
        before = len(ctx.findings)
        ctx.report(node, rule_id, message)
        # move from the per-file list into the tree result, so line
        # ignore-comment suppressions apply normally
        if len(ctx.findings) > before:
            findings.append(ctx.findings.pop())

    reg = wr.aggregate([wr.facts_of(c) for c in wire_contexts(ctxs)])

    # -- wire.token-collision: one namespace at a time. Frame ids and
    # endpoint tokens are DIFFERENT namespaces (TOKEN_RESOLVE == 0x0101
    # == the CommitTransaction frame id is fine; two frames on 0x0101
    # is not).
    by_id: dict[int, list] = {}
    for f in reg.frames:
        by_id.setdefault(f.type_id, []).append(f)
    for type_id, decls in sorted(by_id.items()):
        if len(decls) > 1:
            names = ", ".join(d.name for d in decls)
            for d in decls[1:]:
                report(
                    d.path, d.node, R_COLLISION,
                    f"frame id 0x{type_id:04x} registered twice: {names}",
                )
    by_val: dict[int, list] = {}
    for t in reg.tokens:
        by_val.setdefault(t.value, []).append(t)
    for value, decls in sorted(by_val.items()):
        if len(decls) > 1:
            names = ", ".join(d.name for d in decls)
            for d in decls[1:]:
                report(
                    d.path, d.node, R_COLLISION,
                    f"endpoint token 0x{value:04x} bound twice: {names}",
                )

    # -- wire.codec-field-drift: hand-written pairs only. `_message`
    # frames generate encode and decode from ONE kinds list — drift is
    # impossible by construction, which is exactly the serializer(...)
    # property this rule enforces on the pairs written by hand.
    for f in reg.frames:
        if f.style != "handwritten":
            continue
        enc = reg.codec_funcs.get(f.encoder or "")
        dec = reg.codec_funcs.get(f.decoder or "")
        if enc is None or dec is None:
            continue  # registered from a module the pass can't see
        funcs = {name: fn for name, (_p, fn) in reg.codec_funcs.items()}
        w_ops = wr.expand_ops(wr.encoder_ops(enc[1]), funcs, "w")
        r_ops = wr.expand_ops(wr.decoder_ops(dec[1]), funcs, "r")
        if w_ops != r_ops:
            report(
                f.path, f.node, R_DRIFT_CODEC,
                f"{f.name}: encoder op stream "
                f"[{wr.ops_signature(w_ops)}] != decoder "
                f"[{wr.ops_signature(r_ops)}]",
            )
            continue
        wf = wr.encoder_fields(enc[1])
        rf = wr.decoder_fields(dec[1])
        # `span` unpacks via an attribute read either way; only flag
        # fields one side has and the other lacks entirely
        only_w = sorted(wf - rf - {"span"})
        only_r = sorted(rf - wf)
        if only_w or only_r:
            detail = []
            if only_w:
                detail.append(f"encoded but never decoded: {only_w}")
            if only_r:
                detail.append(f"decoded but never encoded: {only_r}")
            report(
                f.path, f.node, R_DRIFT_CODEC,
                f"{f.name}: {'; '.join(detail)}",
            )

    # -- wire.epoch-unfenced-handler: only REGISTERED handlers (helpers
    # like _resolve_ordered run behind an already-fenced entry point,
    # and the in-process Resolver shares method names but is never
    # token-dispatched). Registration scope is per-file: the module
    # that registers a token names the handler it dispatches to.
    epoch_frames = reg.epoch_frames()
    registered = {
        (r.path, r.handler) for r in reg.handler_regs if r.handler
    }
    for hd in reg.handler_defs:
        if (hd.path, hd.method) not in registered \
                or hd.frame not in epoch_frames:
            continue
        effect = unfenced_effect(hd.node)
        if effect is not None:
            where = f"{hd.cls}.{hd.method}" if hd.cls else hd.method
            report(
                hd.path, effect, R_UNFENCED,
                f"{where}({hd.frame}) reaches an await/state mutation "
                "before the stale_epoch fence",
            )

    # -- wire.call-without-timeout / wire.unclassified-error
    for site in reg.call_sites:
        if not site.has_timeout:
            report(
                site.path, site.node, R_NO_TIMEOUT,
                f"conn.call({site.token}, ...) has no explicit timeout=",
            )
        if not site.classified:
            report(
                site.path, site.node, R_UNCLASSIFIED,
                f"conn.call({site.token}, ...) errors escape "
                "unclassified (no enclosing transport-aware except)",
            )

    # -- wire.manifest-drift
    cur = reg.manifest()
    stored = manifest_mod.load_wire_manifest(manifest_path)
    empty_tree = not cur["frames"] and not cur["tokens"]
    if stored != cur and not (not stored and empty_tree):
        detail = _manifest_diff(stored, cur)
        set_changed = (
            stored.get("frames") != cur["frames"]
            or stored.get("tokens") != cur["tokens"]
        )
        if stored and set_changed and (
            stored.get("protocol_version") == cur["protocol_version"]
        ):
            message = (
                f"wire message set changed without a PROTOCOL_VERSION "
                f"bump ({detail}); bump wire/codec.py PROTOCOL_VERSION "
                "and run --write-wire-manifest"
            )
        else:
            message = f"{detail} (run --write-wire-manifest)"
        findings.append(Finding(
            path=("foundationdb_tpu/analysis/"
                  + manifest_mod.WIRE_MANIFEST_NAME),
            line=1,
            rule=R_DRIFT_MANIFEST,
            message=message,
        ))
    return findings


def tree_wire_manifest(ctxs: list[FileContext]) -> dict:
    """The manifest payload for --write-wire-manifest."""
    reg = wr.aggregate([wr.facts_of(c) for c in wire_contexts(ctxs)])
    return reg.manifest()
