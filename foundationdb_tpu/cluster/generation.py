"""Generation/epoch state machine shared by sim and wire recovery.

The reference rebuilds the transaction system as a unit in a NEW
generation on any transaction-path failure (ClusterRecovery.actor.cpp,
states in RecoveryState.h:31-41). Two deployments replay that shape
here — the deterministic sim (`cluster/recovery.py`) and the wire
cluster controller (`cluster/multiprocess.py` ClusterControllerRole) —
and this module is the ONE place the shared semantics live so the two
cannot drift:

* the recovery state names (RecoveryState.h vocabulary) and the
  `MasterRecoveryState` trace-event shape both emit, so one
  reconstructor (`utils/commit_debug.recovery_timeline`) reads either
  deployment's trace;
* the recovery-version rule (strictly above anything the old
  generation could have allocated, plus the MAX_VERSIONS_IN_FLIGHT
  safety gap);
* the conservative whole-keyspace blind write the new generation's
  first batch carries, so every in-flight transaction whose read
  snapshot predates recovery aborts (the reference's lastEpochEnd
  conflict range);
* the stale-epoch rejection contract: traffic from a pre-recovery
  generation is fenced BY EPOCH (a retryable error with a recognizable
  marker), never by luck.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.utils.trace import TraceEvent

# ---------------------------------------------------------------------------
# Recovery states (RecoveryState.h names, the subset both deployments
# walk; values are the StatusCode strings the trace events carry).

READING_TRANSACTION_SYSTEM_STATE = "reading_transaction_system_state"
LOCKING_OLD_TRANSACTION_SERVERS = "locking_old_transaction_servers"
RECRUITING_TRANSACTION_SERVERS = "recruiting_transaction_servers"
RECOVERY_TRANSACTION = "recovery_transaction"
ACCEPTING_COMMITS = "accepting_commits"
FULLY_RECOVERED = "fully_recovered"

#: canonical walk order — a recovery timeline must visit these in order
#: (later entries may be skipped only if the recovery failed/restarted)
RECOVERY_STATES = (
    READING_TRANSACTION_SYSTEM_STATE,
    LOCKING_OLD_TRANSACTION_SERVERS,
    RECRUITING_TRANSACTION_SERVERS,
    RECOVERY_TRANSACTION,
    ACCEPTING_COMMITS,
    FULLY_RECOVERED,
)

#: the reference's MAX_VERSIONS_IN_FLIGHT safety gap: new-generation
#: versions can never collide with anything the old one allocated
RECOVERY_VERSION_GAP = 1_000_000

#: the conservative-abort blind write: the whole keyspace, so any
#: in-flight transaction with a pre-recovery read snapshot conflicts
CONSERVATIVE_ABORT_RANGE = (b"", b"\xff\xff")

#: error-message marker for generation fencing; carried inside the
#: RemoteError repr across the wire, matched by is_stale_epoch()
STALE_EPOCH_MARKER = "stale_epoch"

#: recovery-reason prefix for ELASTIC topology changes (ISSUE 15): the
#: controller recruits one more instance of the role the Ratekeeper's
#: binding limiter names, via the SAME generation-bumped recovery walk
#: any configuration change drives (the reference's
#: configuration-change-causes-recovery discipline). The drill and the
#: perf ledger pin the prefix the way the chaos smoke pins "push:".
ELASTIC_REASON_PREFIX = "elastic:"


def elastic_reason(kind: str, new_count: int) -> str:
    """The recovery reason an elastic recruit records, e.g.
    "elastic:resolver->2" — reconstructable from the controller trace
    like any other recovery reason."""
    return f"{ELASTIC_REASON_PREFIX}{kind}->{new_count}"


def is_elastic_reason(reason) -> bool:
    return str(reason or "").startswith(ELASTIC_REASON_PREFIX)


def recovery_version_for(*durable_versions: int) -> int:
    """The new generation's recovery version: strictly above anything
    any role has seen, plus the safety gap."""
    return max((0, *durable_versions)) + RECOVERY_VERSION_GAP


def conservative_recovery_transaction(recovery_version: int) -> CommitTransaction:
    """The new generation's FIRST commit: a blind write over the whole
    keyspace at the recovery version. It has no reads, so it always
    commits; registering the write in the (empty) new resolvers makes
    every later transaction whose read snapshot predates recovery
    conflict — the reference's recovery-transaction semantics."""
    return CommitTransaction(
        write_conflict_ranges=[CONSERVATIVE_ABORT_RANGE],
        read_snapshot=recovery_version,
    )


def stale_epoch_message(req_epoch: int, current_epoch: int) -> str:
    """The fencing rejection string (travels inside RemoteError)."""
    return (
        f"{STALE_EPOCH_MARKER}: request epoch {req_epoch} != "
        f"current generation {current_epoch}"
    )


def is_stale_epoch(err) -> bool:
    """True if an exception (or its string form) is a generation-fence
    rejection — the RETRYABLE signal: refresh the topology/epoch from
    the controller and retry at the new generation."""
    return STALE_EPOCH_MARKER in str(err)


# ---------------------------------------------------------------------------
# The state machine object both recovery drivers hold.


@dataclasses.dataclass
class GenerationState:
    """Epoch counter + recovery-state tracker.

    `transition()` is the ONE emitter of the `MasterRecoveryState`
    trace event (Epoch + StatusCode details — the reference's event
    shape), and records the (time, epoch, status) triple on a bounded
    in-memory timeline, so sim and wire recoveries are reconstructable
    through the same vocabulary."""

    epoch: int = 1
    status: str = FULLY_RECOVERED
    recovery_version: int = 0
    #: injected clock (sim passes the virtual scheduler clock; wire
    #: passes time.time so timelines merge with wall-clock trace files)
    clock: Optional[Callable[[], float]] = None
    timeline_cap: int = 64

    def __post_init__(self):
        self.timeline: list[tuple[float, int, str]] = []
        if self.clock is None:
            # wall clock by REFERENCE (never called in sim: every sim
            # construction injects the virtual scheduler clock)
            import time as _time

            self.clock = _time.time

    def _now(self) -> float:
        return self.clock()

    def begin_recovery(self, *, floor: int = 0) -> int:
        """Bump to the next generation (monotonic past `floor`, e.g. a
        persisted epoch from a previous controller incarnation) and
        enter the recovery walk. Returns the new epoch."""
        self.epoch = max(self.epoch + 1, floor + 1)
        self.transition(READING_TRANSACTION_SYSTEM_STATE)
        return self.epoch

    def transition(self, status: str, **details) -> None:
        if status not in RECOVERY_STATES:
            raise ValueError(f"unknown recovery state {status!r}")
        self.status = status
        self.timeline.append((self._now(), self.epoch, status))
        del self.timeline[: -self.timeline_cap]
        ev = TraceEvent("MasterRecoveryState").detail(
            "Epoch", self.epoch
        ).detail("StatusCode", status)
        for k, v in details.items():
            ev.detail(k, v)
        ev.log()

    def timeline_dicts(self) -> list[dict]:
        """The in-memory timeline as JSON-able rows (status payloads)."""
        return [
            {"time": round(t, 6), "epoch": e, "status": s}
            for t, e, s in self.timeline
        ]


def recovery_timeline_from_trace(records: list[dict]) -> list[dict]:
    """Reconstruct the recovery epoch timeline from trace records (the
    JSONL rows utils/commit_debug.load_jsonl yields): every
    MasterRecoveryState event as {"time", "epoch", "status"}, time-
    ordered — works on sim and wire trace files alike because
    GenerationState.transition is the one emitter."""
    rows = [
        {
            "time": float(r.get("Time", 0.0)),
            "epoch": int(r.get("Epoch", 0)),
            "status": r.get("StatusCode", ""),
        }
        for r in records
        if r.get("Type") == "MasterRecoveryState"
    ]
    rows.sort(key=lambda r: (r["time"], r["epoch"]))
    return rows
