"""S3-class blob store: a REST object server + container client.

Capability match for fdbclient/S3BlobStore.actor.cpp (+ the
BackupContainer URL schemes blobstore://...): the reference's backup
and blob-granule stacks talk to an S3-compatible object store over
HTTP — bucket/object PUT/GET/DELETE, prefix listing. This module
provides BOTH halves so the capability is testable with zero egress:

* `serve_blob_store` — a local object server (stdlib http.server,
  threaded) with the S3-ish surface: `PUT /b/<key>` stores bytes,
  `GET /b/<key>` retrieves, `DELETE /b/<key>` removes,
  `GET /b?prefix=` lists keys (newline-separated), ETag = md5 like S3.
* `BlobStoreContainer` — a BackupContainer speaking that protocol via
  http.client, so backups, parallel restore, and blob granules run
  against an object store exactly as the reference's do against S3.

The store persists to a directory (objects as files, names hex-escaped)
so a restarted server still serves its buckets — durability semantics a
backup target needs.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import urllib.parse

from foundationdb_tpu.cluster.backup import (
    BackupContainer,
    _jsonable,
    _unjsonable,
)


def _escape(name: str) -> str:
    return urllib.parse.quote(name, safe="")


def _unescape(name: str) -> str:
    return urllib.parse.unquote(name)


def serve_blob_store(directory: str, port: int = 0):
    """Start the object server; returns (server, port). Caller shuts
    down with server.shutdown()."""
    import http.server

    os.makedirs(directory, exist_ok=True)
    lock = threading.Lock()

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _path(self):
            parsed = urllib.parse.urlparse(self.path)
            parts = parsed.path.lstrip("/").split("/", 1)
            # the URL carries percent-escaped segments; store/serve by
            # the LOGICAL key so listings round-trip
            bucket = _unescape(parts[0])
            key = _unescape(parts[1]) if len(parts) > 1 else ""
            qs = urllib.parse.parse_qs(parsed.query)
            return bucket, key, qs

        def _send(self, code: int, body: bytes = b"",
                  etag: str | None = None):
            self.send_response(code)
            if etag:
                self.send_header("ETag", f'"{etag}"')
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def do_PUT(self):
            bucket, key, _qs = self._path()
            if not bucket or not key:
                self._send(400)
                return
            length = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(length)
            bdir = os.path.join(directory, _escape(bucket))
            with lock:
                os.makedirs(bdir, exist_ok=True)
                tmp = os.path.join(bdir, _escape(key) + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(bdir, _escape(key)))
            self._send(200, etag=hashlib.md5(data).hexdigest())

        def do_GET(self):
            bucket, key, qs = self._path()
            bdir = os.path.join(directory, _escape(bucket))
            if not key:  # list with ?prefix=
                prefix = qs.get("prefix", [""])[0]
                with lock:
                    if not os.path.isdir(bdir):
                        self._send(200, b"")
                        return
                    names = sorted(
                        _unescape(f)
                        for f in os.listdir(bdir)
                        if not f.endswith(".tmp")
                    )
                body = "\n".join(
                    n for n in names if n.startswith(prefix)
                ).encode()
                self._send(200, body)
                return
            path = os.path.join(bdir, _escape(key))
            with lock:
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except FileNotFoundError:
                    self._send(404)
                    return
            self._send(200, data, etag=hashlib.md5(data).hexdigest())

        def do_DELETE(self):
            bucket, key, _qs = self._path()
            path = os.path.join(directory, _escape(bucket), _escape(key))
            with lock:
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    self._send(404)
                    return
            self._send(204)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


class BlobStoreError(RuntimeError):
    pass


class BlobStoreContainer(BackupContainer):
    """BackupContainer over the blob-store REST protocol (the
    blobstore:// container class). Values are the same JSON encoding
    the directory container uses, so backups are medium-portable."""

    def __init__(self, endpoint: str, bucket: str = "backup"):
        self.endpoint = endpoint  # "host:port"
        self.bucket = bucket
        self._conn = None  # persistent HTTP/1.1 keep-alive connection

    def _connection(self):
        if self._conn is None:
            import http.client

            host, port = self.endpoint.rsplit(":", 1)
            self._conn = http.client.HTTPConnection(
                host, int(port), timeout=30
            )
        return self._conn

    def _request(self, method: str, key: str = "", body: bytes = None,
                 query: str = ""):
        path = f"/{_escape(self.bucket)}"
        if key:
            path += f"/{_escape(key)}"
        if query:
            path += f"?{query}"
        # one persistent keep-alive connection per container (a backup
        # writes one object per pulled batch — per-request TCP setup
        # was pure overhead; code review r5); one reconnect retry
        # covers a server-side idle close
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, path, body)
                resp = conn.getresponse()
                data = resp.read()
                break
            except (ConnectionError, OSError):
                self._conn = None
                conn.close()
                if attempt:
                    raise
        if resp.status == 404:
            raise FileNotFoundError(key)
        if resp.status >= 300:
            raise BlobStoreError(f"{method} {path} -> HTTP {resp.status}")
        return data

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def write_file(self, name: str, data) -> None:
        self._request(
            "PUT", name, json.dumps(_jsonable(data)).encode()
        )

    def read_file(self, name: str):
        return _unjsonable(json.loads(self._request("GET", name)))

    def delete_file(self, name: str) -> None:
        self._request("DELETE", name)

    def list_files(self, prefix: str = "") -> list[str]:
        body = self._request(
            "GET", query="prefix=" + urllib.parse.quote(prefix)
        )
        return [n for n in body.decode().split("\n") if n]
