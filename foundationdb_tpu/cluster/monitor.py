"""fdbmonitor analog: supervise role processes, restart them on death.

The reference ships `fdbmonitor` (fdbmonitor/fdbmonitor.cpp, 1,944 LoC):
a small non-Flow supervisor that reads `foundationdb.conf`, launches the
configured fdbserver processes, restarts them with backoff when they die,
and re-reads the conf on SIGHUP. Same contract here for the multiprocess
roles:

* conf: an INI-like file with one `[role.<name>]` section per process —
  role kind, socket address, optional data dir / backend / tlog address
  (for storage catch-up on restart).
* supervision loop: poll children; a dead child is restarted after an
  exponential backoff (reset once it stays up), exactly fdbmonitor's
  delay discipline.
* SIGHUP (or `reload()`): re-read the conf — new sections launch,
  removed sections are stopped.

Used programmatically (`Monitor(conf_path).run_forever()`) or as
`python -m foundationdb_tpu.cluster.monitor <conf>`.
"""

from __future__ import annotations

import configparser
import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Optional

from foundationdb_tpu.cluster.multiprocess import spawn_role


@dataclasses.dataclass
class RoleSpec:
    name: str
    kind: str  # resolver | tlog | storage | ratekeeper | worker | controller
    socket_dir: str
    index: int = 0
    backend: str = "native"
    data_dir: Optional[str] = None
    tlog_address: Optional[str] = None
    storage_engine: str = "memory"
    encrypt: bool = False
    #: ratekeeper: comma list of peer role sockets whose StatusRequest
    #: sensors feed the admission law
    peers: Optional[str] = None
    #: worker/ratekeeper: the cluster controller's socket — under the
    #: controller, the monitor is the DUMB process babysitter (restart
    #: dead processes, nothing else); recruitment and recovery belong
    #: to the controller (cluster/multiprocess.py ClusterControllerRole)
    controller: Optional[str] = None
    #: controller: JSON file with the declarative topology
    cluster_conf: Optional[str] = None
    #: controller: persisted-epoch file (the coordinated-state analog)
    state_file: Optional[str] = None

    @property
    def address(self) -> str:
        return os.path.join(self.socket_dir, f"{self.kind}{self.index}.sock")


def parse_conf(path: str) -> dict[str, RoleSpec]:
    """Parse the foundationdb.conf-style role file."""
    cp = configparser.ConfigParser()
    with open(path) as f:
        cp.read_file(f)
    specs: dict[str, RoleSpec] = {}
    addresses: dict[str, str] = {}
    for section in cp.sections():
        if not section.startswith("role."):
            continue
        name = section[len("role."):]
        sec = cp[section]
        spec = RoleSpec(
            name=name,
            kind=sec["kind"],
            socket_dir=sec["socket_dir"],
            index=sec.getint("index", 0),
            backend=sec.get("backend", "native"),
            data_dir=sec.get("data_dir", None),
            tlog_address=sec.get("tlog_address", None),
            storage_engine=sec.get("storage_engine", "memory"),
            encrypt=sec.getboolean("encrypt", False),
            peers=sec.get("peers", None),
            controller=sec.get("controller", None),
            cluster_conf=sec.get("cluster_conf", None),
            state_file=sec.get("state_file", None),
        )
        if spec.address in addresses:
            raise ValueError(
                f"[role.{name}] and [role.{addresses[spec.address]}] share "
                f"socket {spec.address}: give them distinct index values"
            )
        addresses[spec.address] = name
        specs[name] = spec
    return specs


@dataclasses.dataclass
class _Child:
    spec: RoleSpec
    proc: object  # RoleProcess
    started_at: float
    backoff: float
    restart_at: Optional[float] = None  # set while waiting out a backoff


class Monitor:
    """Supervises one conf's role processes (fdbmonitor's loop)."""

    INITIAL_BACKOFF = 0.2
    MAX_BACKOFF = 30.0
    #: uptime after which the backoff resets (fdbmonitor's restart delay
    #: resets once the child proves stable)
    STABLE_AFTER = 5.0

    def __init__(self, conf_path: str, *, log=print):
        self.conf_path = conf_path
        self.log = log
        self.children: dict[str, _Child] = {}
        self.restarts: dict[str, int] = {}
        self.death_notifies = 0
        self._stop = False
        self._want_reload = False
        self._child_died = False  # SIGCHLD flag: poll now, don't wait

    # -- lifecycle -------------------------------------------------------

    def start_all(self) -> None:
        for name, spec in parse_conf(self.conf_path).items():
            if name not in self.children:
                self._launch(spec)

    def _launch(self, spec: RoleSpec) -> None:
        # a stale socket from a dead child blocks rebinding
        try:
            os.unlink(spec.address)
        except FileNotFoundError:
            pass
        proc = spawn_role(
            spec.kind,
            spec.socket_dir,
            backend=spec.backend,
            index=spec.index,
            data_dir=spec.data_dir,
            tlog_address=spec.tlog_address,
            storage_engine=spec.storage_engine,
            # without this, a supervised restart of an encrypted store
            # would crash-loop on the ENCRYPTION_MODE marker
            encrypt=spec.encrypt,
            peers=spec.peers.split(",") if spec.peers else None,
            controller=spec.controller,
            # the conf NAME is the worker's stable identity: a restarted
            # worker re-registers as itself and the controller sees the
            # same worker with an empty role map (role died with it)
            worker_id=spec.name if spec.kind == "worker" else None,
            cluster_conf=spec.cluster_conf,
            state_file=spec.state_file,
        )
        self.children[spec.name] = _Child(
            spec=spec, proc=proc, started_at=time.monotonic(),
            backoff=self.INITIAL_BACKOFF,
        )
        self.log(f"[monitor] launched {spec.name} ({spec.kind}) "
                 f"pid={proc.proc.pid}")

    def poll_once(self) -> None:
        """One supervision pass: restart whatever died (with backoff).

        Never blocks: a dead child gets a restart DEADLINE and is
        relaunched on a later pass once its backoff elapses, so one
        crash-looping role cannot stall supervision of the others (or
        signal handling) — fdbmonitor's per-process delay discipline.
        """
        now = time.monotonic()
        for name, child in list(self.children.items()):
            if child.restart_at is not None:
                if now >= child.restart_at:
                    self.restarts[name] = self.restarts.get(name, 0) + 1
                    backoff = min(child.backoff * 2, self.MAX_BACKOFF)
                    self._launch(child.spec)
                    self.children[name].backoff = backoff
                continue
            rc = child.proc.proc.poll()
            if rc is None:
                if now - child.started_at > self.STABLE_AFTER:
                    child.backoff = self.INITIAL_BACKOFF
                continue
            self.log(f"[monitor] {name} died rc={rc}; restarting in "
                     f"{child.backoff:.1f}s")
            # PUSH-ON-DEATH (ISSUE 14): tell the controller NOW — one
            # supervision poll of detection latency instead of the
            # controller waiting out HEARTBEAT_MISSES status polls
            # (the PR-13 drill's detection-dominated ~1s)
            self._notify_death(child.spec, rc)
            child.restart_at = now + child.backoff

    def _notify_death(self, spec: RoleSpec, rc) -> None:
        """Best-effort WorkerDeath push to the controller the dead
        worker was registered with. Failure degrades to the heartbeat
        backstop (a dead controller will learn from beacons once the
        monitor restarts it); the call is bounded so a hung controller
        cannot stall supervision of the other children."""
        if not spec.controller or spec.kind == "controller":
            return
        import asyncio
        import json

        from foundationdb_tpu.cluster import multiprocess as mp

        async def _send():
            conn = mp.transport.RpcConnection(spec.controller)
            await conn.connect(retries=1, delay=0.05)
            try:
                # classification boundary is _notify_death's outer
                # `except Exception` around asyncio.run(_send()):
                # death-push failure is logged, never fatal
                await conn.call(  # flowcheck: ignore[wire.unclassified-error]
                    mp.TOKEN_WORKER_DEATH,
                    mp.WorkerDeath(payload=json.dumps({
                        "worker_id": spec.name,
                        "kind": spec.kind,
                        "address": spec.address,
                        "rc": rc,
                    })),
                    timeout=2.0,
                )
            finally:
                await conn.close()

        try:
            asyncio.run(asyncio.wait_for(_send(), 2.5))
            self.death_notifies += 1
            self.log(f"[monitor] pushed {spec.name} death to controller")
        except Exception as e:
            self.log(f"[monitor] death push failed (heartbeat backstop "
                     f"will catch it): {e!r}")

    def reload(self) -> None:
        """Re-read the conf: launch new sections, stop removed ones, and
        RESTART sections whose spec changed (fdbmonitor restarts changed
        processes; a crash-restart must never resurrect a stale spec)."""
        specs = parse_conf(self.conf_path)
        for name in [n for n in self.children if n not in specs]:
            self.log(f"[monitor] {name} removed from conf; stopping")
            self.children.pop(name).proc.stop()
        for name, spec in specs.items():
            if name not in self.children:
                self._launch(spec)
            elif self.children[name].spec != spec:
                self.log(f"[monitor] {name} conf changed; restarting")
                self.children.pop(name).proc.stop()
                self._launch(spec)

    def stop_all(self) -> None:
        self._stop = True
        for child in self.children.values():
            child.proc.stop()
        self.children.clear()

    def run_forever(self, *, poll_interval: float = 0.25) -> None:
        """Supervision loop. Signal handlers only SET FLAGS; the loop acts
        on them between passes — mutating children from a handler mid-pass
        could leak an orphan child or resurrect a removed role
        (fdbmonitor serializes signals into its main loop the same way).
        """
        self.start_all()
        signal.signal(
            signal.SIGHUP,
            lambda *_: setattr(self, "_want_reload", True),
        )
        signal.signal(
            signal.SIGTERM, lambda *_: setattr(self, "_stop", True)
        )
        # SIGCHLD: a dead child triggers an IMMEDIATE supervision pass
        # (the push-on-death latency is then one signal delivery, not a
        # poll interval). The handler only sets a flag — fdbmonitor's
        # serialize-signals-into-the-loop discipline.
        signal.signal(
            signal.SIGCHLD,
            lambda *_: setattr(self, "_child_died", True),
        )
        try:
            while not self._stop:
                if self._want_reload:
                    self._want_reload = False
                    try:
                        self.reload()
                    except Exception as e:
                        # a bad conf must not kill the monitor: keep
                        # supervising with the old one (fdbmonitor's
                        # behavior on an unparseable reload)
                        self.log(f"[monitor] reload failed, keeping old "
                                 f"conf: {e}")
                self._child_died = False
                self.poll_once()
                # sliced sleep: SIGHUP/SIGTERM/SIGCHLD all cut it short
                deadline = time.monotonic() + poll_interval
                while (
                    time.monotonic() < deadline
                    and not (self._stop or self._want_reload
                             or self._child_died)
                ):
                    time.sleep(0.02)
        finally:
            self.stop_all()  # never orphan children, even on a crash


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: python -m foundationdb_tpu.cluster.monitor <conf>",
              file=sys.stderr)
        sys.exit(2)
    Monitor(sys.argv[1]).run_forever()


if __name__ == "__main__":
    main()
