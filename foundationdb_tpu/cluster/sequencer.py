"""The Sequencer (Master) role: strictly-increasing commit versions.

Behavioral mirror of `fdbserver/masterserver.actor.cpp`:

* `get_commit_version` (getVersion :154-239): each proxy batch gets a
  half-open (prev_version, version] pair; version advance is
  clamp(VERSIONS_PER_SECOND * elapsed, 1, MAX_READ_TRANSACTION_LIFE_
  VERSIONS) so versions track wall-clock at ~1e6/s — the MVCC window is
  a time window (fdbclient/ServerKnobs.cpp:36-44).
* Request ordering by (requestNum, mostRecentProcessedRequestNum): a
  proxy's out-of-order version requests are queued; duplicates replay the
  cached reply (:160-178 requestNum bookkeeping).
* `report_live_committed_version` / `get_live_committed_version`
  (masterserver.actor.cpp provideVersions/serveLiveCommittedVersion):
  proxies report fully-committed versions; GRV proxies read the max.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from foundationdb_tpu.runtime.flow import Notified, Scheduler

VERSIONS_PER_SECOND = 1_000_000
MAX_READ_TRANSACTION_LIFE_VERSIONS = 5_000_000
MAX_VERSION_RATE_MODIFIER = 0.1


@dataclasses.dataclass
class CommitVersionReply:
    version: int
    prev_version: int
    request_num: int
    # resolver partition changes would ride here (GetCommitVersionReply.
    # resolverChanges, ResolutionBalancer.actor.cpp:36) — static in v0.


class _ProxyVersionState:
    __slots__ = ("latest_request_num", "replies")

    def __init__(self):
        # Proxies number requests from 1; 0 means "none processed yet".
        self.latest_request_num = 0
        self.replies: dict[int, CommitVersionReply] = {}


class Sequencer:
    """Allocates the global commit-version order."""

    def __init__(self, sched: Scheduler, *, recovery_version: int = 0):
        self.sched = sched
        self.version = recovery_version          # last allocated
        self.last_version_time = sched.now()
        self.live_committed = Notified(recovery_version)
        self.committed_version = Notified(recovery_version)  # reported by proxies
        self._proxies: dict[str, _ProxyVersionState] = {}
        self.reference_version: Optional[int] = None

    # -- commit version allocation (getVersion :154-239) -----------------

    async def get_commit_version(
        self, proxy_id: str, request_num: int, most_recent_processed: int
    ) -> Optional[CommitVersionReply]:
        st = self._proxies.setdefault(proxy_id, _ProxyVersionState())
        # Drop replies the proxy has fully processed.
        for rn in [r for r in st.replies if r < most_recent_processed]:
            del st.replies[rn]

        if request_num <= st.latest_request_num:
            # Duplicate / stale: replay if cached, else ignore (the reference
            # sends Never() for requests below the window).
            return st.replies.get(request_num)

        # Wait for in-order request numbers (the reference queues these).
        while request_num > st.latest_request_num + 1:
            await self.sched.delay(0.001)
            if request_num <= st.latest_request_num:
                return st.replies.get(request_num)

        now = self.sched.now()
        elapsed = now - self.last_version_time
        self.last_version_time = now
        to_add = max(
            1,
            min(
                MAX_READ_TRANSACTION_LIFE_VERSIONS,
                int(VERSIONS_PER_SECOND * elapsed),
            ),
        )
        prev = self.version
        self.version = prev + to_add
        st.latest_request_num = request_num
        reply = CommitVersionReply(
            version=self.version, prev_version=prev, request_num=request_num
        )
        st.replies[request_num] = reply
        return reply

    # -- live committed version (GRV path) -------------------------------

    def report_live_committed_version(self, version: int) -> None:
        if version > self.live_committed.get():
            self.live_committed.set(version)

    def get_live_committed_version(self) -> int:
        return self.live_committed.get()
