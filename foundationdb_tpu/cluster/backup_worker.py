"""BackupWorker: the per-epoch log-tailing backup role.

Capability match for fdbserver/BackupWorker.actor.cpp: a worker is
recruited FOR ONE LOG EPOCH, tails the full mutation stream into log
files in the backup container, advances a saved-version watermark (the
"popped" position other components may garbage-collect behind), and on
recovery is DISPLACED — it drains exactly what its epoch committed,
writes the tail, and exits so the next epoch's worker continues from
its watermark. The epoch manager mirrors the cluster controller's
recruitment loop (worker.actor.cpp backup recruitment): one worker per
epoch, chained watermarks, no gap and no double-write across the
recovery boundary.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import ActorCancelled, Promise
from foundationdb_tpu.utils.probes import code_probe, declare
from foundationdb_tpu.utils.trace import TraceEvent

declare("backup_worker.displaced")


class BackupWorker:
    """Tails LOG_STREAM_TAG for one epoch into `container`."""

    def __init__(self, sched, tlog, container, *, epoch: int,
                 start_version: int = 0, consumer: str = "backup",
                 own_consumer: bool = True):
        self.sched = sched
        self.tlog = tlog
        self.container = container
        self.epoch = epoch
        self.saved_version = start_version
        self.consumer = consumer
        # Under a manager, the MANAGER owns the consumer registration:
        # if the displaced worker unregistered on stop, any mutation
        # committed between its last peek and the successor's
        # registration would be trimmed from the tlog — a silent,
        # permanent gap in the backup log (code review r5). Standalone
        # workers (tests) still own their registration.
        self.own_consumer = own_consumer
        self.displaced = Promise()
        self._task = None

    def start(self) -> None:
        if self.own_consumer:
            self.tlog.register_consumer(self.consumer)
        self._task = self.sched.spawn(
            self._pull(), name=f"backup-worker-e{self.epoch}"
        )

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self.own_consumer:
            self.tlog.unregister_consumer(self.consumer)

    def _write(self, entries: dict) -> None:
        if not entries:
            return
        # zero-padded version keys: restore sorts these strings, so
        # unpadded digits would replay out of numeric order
        self.container.write_file(
            f"logs/{min(entries):016d}",
            {f"{v:016d}": m for v, m in sorted(entries.items())},
        )

    async def _pull(self) -> None:
        from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG

        try:
            after = self.saved_version
            while True:
                displaced = self.tlog.epoch > self.epoch
                got, log_version = await self.tlog.peek(
                    LOG_STREAM_TAG, after
                )
                entries = {v: msgs for v, msgs in got if msgs}
                self._write(entries)
                after = max(log_version, max(entries, default=0))
                self.saved_version = after
                self.tlog.pop(LOG_STREAM_TAG, after, consumer=self.consumer)
                if displaced or self.tlog.epoch > self.epoch:
                    # drained through the lock version: everything this
                    # epoch committed is in the container — hand off
                    code_probe(True, "backup_worker.displaced")
                    TraceEvent("BackupWorkerDone").detail(
                        "Epoch", self.epoch
                    ).detail("SavedVersion", after).log()
                    break
                await self.tlog.version.when_at_least(after + 1)
        except ActorCancelled:
            raise
        finally:
            if not self.displaced.is_set:
                self.displaced.send(self.saved_version)


class BackupWorkerManager:
    """Recruit one BackupWorker per log epoch, chaining watermarks —
    the CC's backup-recruitment loop in miniature. Survives recoveries:
    when the epoch bumps, the displaced worker finishes its epoch and
    the manager recruits the next one from its watermark."""

    CONSUMER = "backup"

    def __init__(self, sched, cluster_ref, container,
                 start_version: int = 0):
        self.sched = sched
        self._cluster = cluster_ref  # callable -> cluster (tlog may change)
        self.container = container
        self.saved_version = start_version
        self.worker: BackupWorker | None = None
        self._task = None
        self._tlog = None

    def start(self) -> None:
        self._task = self.sched.spawn(self._manage(), name="backup-manager")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self.worker is not None:
            self.worker.stop()
        if self._tlog is not None:
            self._tlog.unregister_consumer(self.CONSUMER)

    async def _manage(self) -> None:
        try:
            prev = None
            while True:
                tlog = self._cluster().tlog
                # the registration is CONTINUOUS across worker swaps —
                # registering the (possibly new) tlog BEFORE stopping
                # the displaced worker means no commit can be trimmed
                # in the handoff window (code review r5)
                tlog.register_consumer(self.CONSUMER)
                self._tlog = tlog
                if prev is not None:
                    prev.stop()
                self.worker = BackupWorker(
                    self.sched, tlog, self.container,
                    epoch=tlog.epoch, start_version=self.saved_version,
                    consumer=self.CONSUMER, own_consumer=False,
                )
                self.worker.start()
                # prev deliberately holds the PREVIOUS epoch's worker
                # across the displacement wait — a stale handle is the
                # point (the successor stops its predecessor)
                prev = self.worker  # flowcheck: ignore[flow.stale-read-across-wait]
                self.saved_version = await self.worker.displaced.future
        except ActorCancelled:
            raise
