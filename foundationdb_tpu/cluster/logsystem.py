"""LogSystem: replicated transaction logs.

Behavioral mirror of the reference's TagPartitionedLogSystem
(fdbserver/TagPartitionedLogSystem.actor.cpp) at its core contract: a
commit is durable only when EVERY (live) log replica has it (the push
quorum is all-of-policy in the reference too — lagging/dead logs force
recovery, they never silently reduce durability); peeks are served by
any live replica (they hold identical streams); pops forward to all; the
epoch lock applies to the whole generation.

The LogSystem exposes the same surface as a single TLog (commit / peek /
pop / version / lock / consumer registration), so storage servers,
backup workers, and commit proxies use it unchanged.
"""

from __future__ import annotations

from foundationdb_tpu.cluster.tlog import TLog, TLogCommitRequest
from foundationdb_tpu.runtime.flow import Notified, Scheduler, all_of


class AllLogsDeadError(Exception):
    """No live log replica remains — the cluster cannot commit."""


class LogSystem:
    def __init__(self, sched: Scheduler, n_logs: int = 1, *,
                 recovery_version: int = 0, durable: bool = True,
                 n_satellites: int = 0):
        from foundationdb_tpu.sim.diskqueue import SimDiskQueue

        self.sched = sched
        # Every sim replica writes through a SimDiskQueue so simulation
        # seeds exercise the DiskQueue recovery-scan path (the
        # one-abstraction-two-backends discipline; the multiprocess
        # deployment uses the native queue, native/diskqueue.cpp).
        self.tlogs = [
            TLog(
                sched,
                recovery_version=recovery_version,
                durable=SimDiskQueue() if durable else None,
            )
            for _ in range(n_logs)
        ]
        self.live = [True] * n_logs
        # Satellite logs: replicas in a SECOND failure domain of the
        # primary region that hold only the full mutation stream
        # (ha-write-path.rst: "satellite transaction logs only store the
        # log router tags"). Commits ack only after satellites are
        # durable too, so a whole-primary-DC death leaves the acked
        # suffix recoverable from them (RPO=0 — the r3 PARITY gap).
        self.satellites = [
            TLog(
                sched,
                recovery_version=recovery_version,
                durable=SimDiskQueue() if durable else None,
            )
            for _ in range(n_satellites)
        ]
        self.satellite_live = [True] * n_satellites
        # The system-level durable version: set once every live replica
        # has acked a push (what proxies/storages chain on).
        self.version = Notified(recovery_version)
        self.epoch = 1

    # -- replica selection -------------------------------------------------

    def _live_logs(self) -> list[TLog]:
        logs = [t for t, alive in zip(self.tlogs, self.live) if alive]
        if not logs:
            raise AllLogsDeadError()
        return logs

    def kill(self, i: int) -> None:
        """Mark log replica i dead (its state freezes; it no longer
        participates in pushes, peeks, or pops)."""
        self.live[i] = False
        self._live_logs()  # raises if that was the last one

    def kill_dc(self) -> None:
        """Whole-primary-DC death: EVERY main log replica dies at once
        (no last-replica guard — this is the disaster, not an operation).
        Satellites live in a different failure domain and survive;
        subsequent commits/peeks raise AllLogsDeadError until a region
        failover promotes the remote."""
        self.live = [False] * len(self.live)

    def _live_satellites(self) -> list[TLog]:
        return [
            t for t, alive in zip(self.satellites, self.satellite_live)
            if alive
        ]

    def kill_satellite(self, i: int) -> None:
        self.satellite_live[i] = False

    def crash_and_reboot(self, i: int, rng=None) -> None:
        """Power-loss the replica's simulated disk (un-fsynced data may
        tear — AsyncFileNonDurable semantics), run the DiskQueue
        recovery scan, then catch the replica up from a live peer and
        return it to service. The sim analog of a tlog process reboot."""
        t = self.tlogs[i]
        # find the peer BEFORE marking dead: if none exists, refuse
        # without corrupting the live set (the replica is still healthy)
        peer = next(
            (
                tl
                for j, (tl, alive) in enumerate(zip(self.tlogs, self.live))
                if alive and j != i
            ),
            None,
        )
        if peer is None:
            raise AllLogsDeadError("no live peer to catch up from")
        self.live[i] = False
        if t.dq is not None:
            t.dq.crash(rng)
            t.restore_from_disk()
        t.catch_up_from(peer)
        self.live[i] = True

    # -- the TLog-compatible surface --------------------------------------

    async def commit(self, req: TLogCommitRequest) -> int:
        # span-threaded push: one child of the proxy's commitBatch span
        # per log-system push (not per replica — the replicas share the
        # ack barrier below)
        span = None
        if req.span is not None:
            from foundationdb_tpu.utils.spans import Span, SpanContext

            span = Span(
                "tlog.push", parent=SpanContext(*req.span),
                clock=self.sched.now,
            ).attribute("Version", req.version)
        try:
            return await self._commit_spanned(req)
        finally:
            if span is not None:
                span.finish()

    async def _commit_spanned(self, req: TLogCommitRequest) -> int:
        logs = self._live_logs()
        tasks = [self.sched.spawn(t.commit(req)).done for t in logs]
        if self.satellites:
            # Satellite push rides the SAME ack barrier as the main
            # replicas: the commit is not acked until the stream is
            # durable in the second failure domain (the HA write path's
            # RPO=0 contract). Satellites store only the full-stream
            # tag — per-storage tags never leave the main DC.
            from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG

            sat_msgs = {}
            if LOG_STREAM_TAG in req.messages:
                sat_msgs[LOG_STREAM_TAG] = req.messages[LOG_STREAM_TAG]
            sat_req = TLogCommitRequest(
                prev_version=req.prev_version,
                version=req.version,
                messages=sat_msgs,
                known_committed_version=req.known_committed_version,
                epoch=req.epoch,
            )
            tasks += [
                self.sched.spawn(t.commit(sat_req)).done
                for t in self._live_satellites()
            ]
        results = await all_of(tasks)
        v = max(results)
        if v > self.version.get():
            self.version.set(v)
        return v

    async def peek(self, tag: int, after_version: int):
        # any live replica serves (identical streams); wait on the
        # system version so a mid-wait kill cannot strand the waiter on
        # a frozen replica's Notified
        await self.version.when_at_least(after_version + 1)
        return await self._live_logs()[0].peek(tag, after_version)

    def pop(self, tag: int, up_to_version: int, consumer: str = "storage"):
        for t in self._live_logs():
            t.pop(tag, up_to_version, consumer)
        for t in self._live_satellites():
            t.pop(tag, up_to_version, consumer)

    def tag_backlog_bytes(self, tag: int, consumer: str = "storage") -> int:
        """Worst retained bytes for one consumer's tag across live
        replicas (the per-storage write-queue sensor: replicas hold the
        same stream, so the slowest-trimmed one is the honest depth).
        Dead replicas don't report — a frozen log isn't a queue."""
        return max(
            (
                t.tag_backlog_bytes(tag, consumer)
                for t, alive in zip(self.tlogs, self.live)
                if alive
            ),
            default=0,
        )

    def has_log_consumers(self) -> bool:
        return any(t.has_log_consumers() for t in self._live_logs())

    @property
    def tag_partitioned(self) -> bool:
        """The REAL per-tag fan-out state (ISSUE 20, PR-19 remaining
        (b)): True once commits have fanned out to more than one
        per-storage tag stream inside this log front. The wire pipeline
        reports True when its tlogs are key-range partitioned; here the
        partitioning lives inside the replicas' tag-keyed streams — the
        sensor means "mutations are routed per tag" on both paths."""
        from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG

        tags: set = set()
        for t, alive in zip(self.tlogs, self.live):
            if alive:
                tags.update(t._messages)
                tags.update(t._spilled)
        tags.discard(LOG_STREAM_TAG)
        return len(tags) > 1

    def register_consumer(self, name: str) -> None:
        for t in self.tlogs + self.satellites:
            t.register_consumer(name)

    def register_tag_mirror(self, tag: int, name: str) -> None:
        for t in self.tlogs + self.satellites:
            t.register_tag_mirror(tag, name)

    def unregister_tag_mirror(self, tag: int, name: str) -> None:
        for t in self.tlogs + self.satellites:
            t.unregister_tag_mirror(tag, name)

    def unregister_consumer(self, name: str) -> None:
        for t in self.tlogs + self.satellites:
            t.unregister_consumer(name)

    def lock(self, epoch: int, recovery_version: int = None) -> None:
        self.epoch = max(self.epoch, epoch)
        # dead replicas and satellites lock too: no zombie pushes
        for t in self.tlogs + self.satellites:
            t.lock(epoch, recovery_version)
        if recovery_version is not None and recovery_version > self.version.get():
            self.version.set(recovery_version)
