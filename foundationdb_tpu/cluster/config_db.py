"""Dynamic knob configuration: versioned overrides broadcast to roles.

Behavioral mirror of the reference's dynamic-knobs subsystem
(design/dynamic-knobs.md; fdbserver/ConfigNode.actor.cpp +
ConfigBroadcaster.actor.cpp + LocalConfiguration.actor.cpp), using this
build's own primitives: overrides are committed transactionally into the
`\\xff/conf/` keyspace (the ConfigNode's versioned store), and each
process's LocalConfiguration watches the generation key and re-applies
the full override set to its live Knobs object when it changes — roles
see knob changes without restarts, in commit order.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.knobs import Knobs

CONF_PREFIX = b"\xff/conf/"
CONF_GENERATION = b"\xff/confGeneration"


async def set_knob(db, name: str, value) -> None:
    """Commit one knob override (fdbcli `setknob`)."""
    txn = db.create_transaction()
    txn.set(CONF_PREFIX + name.encode(), repr(value).encode())
    txn.add(CONF_GENERATION, 1)
    await txn.commit()


async def clear_knob(db, name: str) -> None:
    txn = db.create_transaction()
    txn.clear(CONF_PREFIX + name.encode())
    txn.add(CONF_GENERATION, 1)
    await txn.commit()


async def read_overrides(db) -> dict[str, object]:
    txn = db.create_transaction()
    items = await txn.get_range(CONF_PREFIX, CONF_PREFIX + b"\xff")
    import ast

    return {
        k[len(CONF_PREFIX):].decode(): ast.literal_eval(v.decode())
        for k, v in items
    }


class LocalConfiguration:
    """Per-process knob view: defaults + broadcast overrides
    (LocalConfiguration.actor.cpp)."""

    def __init__(self, db, knobs: Knobs):
        self.db = db
        self.knobs = knobs
        self.generation = 0
        self._task = None

    def start(self) -> None:
        self._task = self.db.sched.spawn(self._watch(), name="local-config")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def refresh(self) -> None:
        overrides = await read_overrides(self.db)
        self.knobs.reset()
        for name, value in overrides.items():
            try:
                self.knobs.set(name, value)
            except KeyError:
                pass  # unknown knob: ignored, as the reference does
        txn = self.db.create_transaction()
        raw = await txn.get(CONF_GENERATION, snapshot=True)
        self.generation = int.from_bytes(raw or b"\0" * 8, "little")

    async def _watch(self) -> None:
        try:
            await self.refresh()
            while True:
                txn = self.db.create_transaction()
                fut = await txn.watch(CONF_GENERATION)
                await fut
                await self.refresh()
        except ActorCancelled:
            raise
