"""Dynamic knob configuration: versioned overrides broadcast to roles.

Behavioral mirror of the reference's dynamic-knobs subsystem
(design/dynamic-knobs.md; fdbserver/ConfigNode.actor.cpp +
PaxosConfigConsumer.actor.cpp + ConfigBroadcaster.actor.cpp +
LocalConfiguration.actor.cpp), using this build's own primitives:

* The AUTHORITATIVE override set lives on the coordinators through
  CoordinatedState (PaxosConfigStore below) — the reference's ConfigNode
  quorum. Knob data therefore survives coordinator minority loss and
  does not depend on the data plane (tlogs/storage) being recoverable.
* Each committed change is then broadcast by writing the overrides into
  the `\\xff/conf/` keyspace and bumping a generation key; every
  process's LocalConfiguration watches the generation key and re-applies
  the full override set to its live Knobs object when it changes — roles
  see knob changes without restarts, in commit order (the
  ConfigBroadcaster push path).
* After a data-plane wipe/recovery, `restore_broadcast` re-seeds the
  keyspace from the quorum (PaxosConfigConsumer catching a broadcaster
  up from the ConfigNodes).
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.knobs import Knobs
from foundationdb_tpu.utils.probes import code_probe, declare

declare(
    "config.quorum_write",
    "config.quorum_write_raced",
    "config.quorum_write_retried",
    "config.restored_from_quorum",
)

CONF_PREFIX = b"\xff/conf/"
CONF_GENERATION = b"\xff/confGeneration"
#: quorum generation of the last broadcast landed in the keyspace —
#: orders racing broadcasts (see _broadcast)
CONF_QUORUM_GEN = b"\xff/confQuorumGeneration"


class PaxosConfigStore:
    """Quorum-held knob overrides (fdbserver/ConfigNode.actor.cpp).

    The value in CoordinatedState is {"generation": int, "overrides":
    {name: repr(value)}}. Mutations are read-modify-write rounds;
    StaleGeneration (a racing writer / deposed generation) retries with
    a fresh read, exactly like PaxosConfigTransaction's commit loop.
    """

    RETRIES = 8
    #: transient-outage budget: a coordinator majority may be down for a
    #: recovery window; the soak kills majorities for ~0.8s virtual, so
    #: the capped-exponential backoff sum (~12s) rides it out easily
    QUORUM_RETRIES = 12
    QUORUM_BACKOFF = 0.05
    QUORUM_BACKOFF_MAX = 2.0

    def __init__(self, sched, coordinators, client_id: str = "config"):
        from foundationdb_tpu.cluster.coordination import CoordinatedState

        self._sched = sched
        self._cs = CoordinatedState(sched, coordinators, client_id)

    async def snapshot(self) -> tuple[int, dict]:
        val = await self._cs.read()
        if not val:
            return 0, {}
        return val["generation"], dict(val["overrides"])

    async def _mutate(self, fn) -> tuple[int, dict]:
        from foundationdb_tpu.cluster.coordination import (
            QuorumUnreachable,
            StaleGeneration,
        )

        # Two independent retry budgets: RMW races (StaleGeneration —
        # another writer won, retry immediately with a fresh read) and
        # transient quorum outages (QuorumUnreachable — a coordinator
        # majority is down, back off and wait for revival). The round-5
        # soak let the second escape the actor entirely: 264 unhandled
        # `config_db.set` tracebacks across 2000 seeds, zero failures
        # (VERDICT "What's weak" §5) — the exact class flowcheck's
        # actor-safety rule + the scheduler's unhandled-error ledger now
        # make structurally loud.
        stale_attempts = 0
        quorum_attempts = 0
        backoff = self.QUORUM_BACKOFF
        while True:
            try:
                gen, overrides = await self.snapshot()
                fn(overrides)
                # a real client pays at least a network round between its
                # read and its write; the in-process Coordinator stubs never
                # suspend, so without this yield two RMW rounds could never
                # interleave and the raced path would be unreachable in sim
                await self._sched.delay(0)
                await self._cs.write(
                    {"generation": gen + 1, "overrides": overrides}
                )
            except StaleGeneration:
                code_probe(True, "config.quorum_write_raced")
                stale_attempts += 1
                if stale_attempts >= self.RETRIES:
                    raise StaleGeneration(
                        "knob write outran %d times" % self.RETRIES
                    )
                continue
            except QuorumUnreachable:
                quorum_attempts += 1
                if quorum_attempts >= self.QUORUM_RETRIES:
                    raise  # outage outlived the budget: fail loudly
                code_probe(True, "config.quorum_write_retried")
                await self._sched.delay(backoff)
                backoff = min(backoff * 2, self.QUORUM_BACKOFF_MAX)
                continue
            code_probe(True, "config.quorum_write")
            return gen + 1, overrides

    async def set(self, name: str, raw: bytes) -> tuple[int, dict]:
        return await self._mutate(lambda o: o.__setitem__(name, raw))

    async def clear(self, name: str) -> tuple[int, dict]:
        return await self._mutate(lambda o: o.pop(name, None))


def _quorum_store(db) -> "PaxosConfigStore | None":
    cluster = getattr(db, "cluster", None)
    if cluster is None or not getattr(cluster, "config_nodes", None):
        return None
    store = getattr(cluster, "_config_store", None)
    if store is None:
        store = PaxosConfigStore(cluster.sched, cluster.config_nodes)
        cluster._config_store = store
    return store


async def _broadcast(db, gen: int, overrides: dict, *,
                     force: bool = False) -> None:
    """Commit the FULL override set into `\\xff/conf/` + bump the
    generation key (the ConfigBroadcaster push: watchers re-apply).

    Ordered by the QUORUM generation: the snapshot read of
    CONF_QUORUM_GEN is a conflict range, so two racing broadcasts
    serialize — the one carrying the older quorum state either aborts
    and re-reads or sees a newer stored generation and stands down.
    Without this, a slower writer's clear_range+rewrite could land
    AFTER a newer one and silently un-apply an acked knob cluster-wide.
    """
    from foundationdb_tpu.cluster.commit_proxy import NotCommitted

    for _attempt in range(8):
        txn = db.create_transaction()
        cur_raw = await txn.get(CONF_QUORUM_GEN)
        cur = int.from_bytes(cur_raw, "big") if cur_raw else 0
        if cur >= gen and not force:
            return  # a broadcast at least this new already landed
        txn.clear_range(CONF_PREFIX, CONF_PREFIX + b"\xff")
        for name, raw in overrides.items():
            txn.set(CONF_PREFIX + name.encode(), raw)
        txn.set(CONF_QUORUM_GEN, gen.to_bytes(8, "big"))
        txn.add(CONF_GENERATION, 1)
        try:
            await txn.commit()
            return
        except NotCommitted:
            continue  # raced: re-read the stored generation
    raise NotCommitted("knob broadcast raced out 8 times")


async def set_knob(db, name: str, value) -> None:
    """Commit one knob override (fdbcli `setknob`): quorum first —
    the write is durable once the coordinators accept it — then the
    keyspace broadcast."""
    store = _quorum_store(db)
    if store is None:  # no coordinators (bare DB): keyspace only
        txn = db.create_transaction()
        txn.set(CONF_PREFIX + name.encode(), repr(value).encode())
        txn.add(CONF_GENERATION, 1)
        await txn.commit()
        return
    gen, overrides = await store.set(name, repr(value).encode())
    await _broadcast(db, gen, overrides)


async def clear_knob(db, name: str) -> None:
    store = _quorum_store(db)
    if store is None:
        txn = db.create_transaction()
        txn.clear(CONF_PREFIX + name.encode())
        txn.add(CONF_GENERATION, 1)
        await txn.commit()
        return
    gen, overrides = await store.clear(name)
    await _broadcast(db, gen, overrides)


async def restore_broadcast(db) -> dict:
    """Re-seed `\\xff/conf/` from the coordinator quorum — the recovery
    path after data-plane loss (the broadcaster's snapshot-from-
    ConfigNodes catch-up). Returns the restored overrides."""
    store = _quorum_store(db)
    if store is None:
        return {}
    gen, overrides = await store.snapshot()
    code_probe(bool(overrides), "config.restored_from_quorum")
    # force: the keyspace copy may have been wiped while the stored
    # CONF_QUORUM_GEN survived (partial loss) — restore must overwrite
    # regardless; the read still serializes racing broadcasts
    await _broadcast(db, gen, overrides, force=True)
    return await read_overrides(db)


async def read_overrides(db, txn=None) -> dict[str, object]:
    # pass `txn` to read at ITS read version (LocalConfiguration.refresh
    # reads overrides + generation in one transaction)
    if txn is None:
        txn = db.create_transaction()
    items = await txn.get_range(CONF_PREFIX, CONF_PREFIX + b"\xff")
    import ast

    return {
        k[len(CONF_PREFIX):].decode(): ast.literal_eval(v.decode())
        for k, v in items
    }


class LocalConfiguration:
    """Per-process knob view: defaults + broadcast overrides
    (LocalConfiguration.actor.cpp)."""

    def __init__(self, db, knobs: Knobs):
        self.db = db
        self.knobs = knobs
        self.generation = 0
        self._task = None

    def start(self) -> None:
        self._task = self.db.sched.spawn(self._watch(), name="local-config")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def refresh(self) -> None:
        # overrides and generation read in ONE transaction (one read
        # version): self.generation is exactly the generation of the
        # override set just applied, so the watch loop's gen compare
        # can detect any commit this refresh missed
        txn = self.db.create_transaction()
        overrides = await read_overrides(self.db, txn=txn)
        raw = await txn.get(CONF_GENERATION, snapshot=True)
        self.knobs.reset()
        for name, value in overrides.items():
            try:
                self.knobs.set(name, value)
            except KeyError:
                pass  # unknown knob: ignored, as the reference does
        self.generation = int.from_bytes(raw or b"\0" * 8, "little")

    async def _watch(self) -> None:
        try:
            await self.refresh()
            while True:
                # read-compare-then-watch, all at ONE read version: a
                # generation bump BETWEEN the last refresh's read
                # version and this transaction's is caught by the
                # compare (refresh again, no watch armed); a bump AFTER
                # this read version fires the watch, whose expected
                # value was read at the same version. The old
                # arm-without-comparing loop silently lost any commit
                # landing in the refresh->watch window until the NEXT
                # bump — exposed by PR-6's adaptive batching shifting
                # GRV/commit timing in the sims.
                txn = self.db.create_transaction()
                raw = await txn.get(CONF_GENERATION, snapshot=True)
                gen = int.from_bytes(raw or b"\0" * 8, "little")
                if gen != self.generation:
                    await self.refresh()
                    continue
                fut = await txn.watch(CONF_GENERATION)
                await fut
                await self.refresh()
        except ActorCancelled:
            raise
