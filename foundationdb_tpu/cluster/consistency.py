"""Consistency checking: storage/shard-map integrity + replica equality.

Behavioral mirror of the reference's ConsistencyCheck workload /
ConsistencyScan role (fdbserver/workloads/ConsistencyCheck.actor.cpp,
fdbserver/ConsistencyScan.actor.cpp): verifies the structural invariants
that shard moves and MVCC maintenance must preserve, and — for
replicated shards — that every live team member holds identical data
for its segments (the reference's core replica comparison), at a
quiescent point.
"""

from __future__ import annotations


class ConsistencyError(AssertionError):
    pass


def check_cluster(cluster) -> dict:
    """Run all invariant checks; returns stats, raises ConsistencyError."""
    sm = cluster.key_servers
    stats = {"keys_checked": 0, "shards_checked": 0, "replica_compares": 0}

    # shard map well-formed: boundaries strictly ascending, owners valid
    for a, b in zip(sm.boundaries, sm.boundaries[1:]):
        if not a < b:
            raise ConsistencyError(f"shard boundaries out of order: {a} {b}")
    n_storage = len(cluster.storage_servers)
    for team in sm.owners:
        for o in team:
            if not 0 <= o < n_storage:
                raise ConsistencyError(f"shard owner {o} out of range")

    owned: dict[int, list] = {s: [] for s in range(n_storage)}
    for b, e, team in sm.ranges():
        for o in team:
            owned[o].append((b, e))
        stats["shards_checked"] += 1

    # replica comparison: all LIVE members of a team agree per segment
    def seg_data(s: int, b: bytes, e) -> dict:
        d = cluster.storage_servers[s]._data
        return {k: v for k, v in d.items() if k >= b and (e is None or k < e)}

    for b, e, team in sm.ranges():
        live = [s for s in team if cluster.storage_live[s]]
        if len(live) > 1:
            base = seg_data(live[0], b, e)
            for s in live[1:]:
                if seg_data(s, b, e) != base:
                    raise ConsistencyError(
                        f"replica divergence in [{b!r}, {e!r}): "
                        f"storage{live[0]} vs storage{s}"
                    )
                stats["replica_compares"] += 1

    for s, ss in enumerate(cluster.storage_servers):
        if not cluster.storage_live[s]:
            continue  # dead replicas keep stale data until repaired/rebooted
        live = 0
        for k in ss._keys:
            h = ss._hist[k]
            # histories strictly version-ascending
            for (v1, _), (v2, _) in zip(h, h[1:]):
                if not v1 < v2:
                    raise ConsistencyError(
                        f"storage{s} key {k!r}: history out of order"
                    )
            if h[-1][1] is not None:
                live += 1
                # every live key must be in a shard this server owns OR
                # in a still-installing fetch range
                in_owned = any(
                    b <= k and (e is None or k < e) for b, e in owned[s]
                )
                in_fetch = any(
                    b <= k < e for (b, e) in ss._fetching
                )
                if not (in_owned or in_fetch):
                    raise ConsistencyError(
                        f"storage{s} holds live key {k!r} outside its shards"
                    )
            stats["keys_checked"] += 1
        if live != ss._live_count:
            raise ConsistencyError(
                f"storage{s} live_count {ss._live_count} != recount {live}"
            )
    return stats
