"""TSS: testing storage servers — mirror pairs that check reads.

Capability match for the reference's TSS feature
(fdbserver/storageserver.actor.cpp TSS paths, fdbrpc/TSSComparison.h,
design in design/tss.md): a TSS is paired with one storage server,
receives the SAME mutation stream (here: it pulls the same tag from
the tag-partitioned log, so it converges on identical content by
construction), and the client DUPLICATES a sample of reads to it —
comparing results out of the request path. A mismatch is a detected
storage-engine divergence: SevError trace + counter + CODE_PROBE; the
TSS answer is never served to the application, and a dead/slow TSS
never delays a client read (the comparison is fire-and-forget).
"""

from __future__ import annotations

from foundationdb_tpu.utils.probes import code_probe, declare
from foundationdb_tpu.utils.trace import SEV_ERROR, TraceEvent

declare("tss.mismatch")

#: every Nth eligible read is duplicated to the TSS pair (the
#: reference's TSS_SAMPLE class of knobs; deterministic counter here —
#: the sim lanes need reproducibility, not randomness)
TSS_SAMPLE_EVERY = 4


class TssComparator:
    """Client-side sampling + comparison state (TSSComparison.h)."""

    def __init__(self, sched, cluster):
        self.sched = sched
        self.cluster = cluster
        self._counter = 0
        self.samples = 0
        self.mismatches = 0

    def maybe_sample(self, server: int, key: bytes, version: int,
                     result) -> None:
        """Fire-and-forget duplicate of a successful get to the TSS
        paired with `server` (if any). Never raises; never blocks the
        caller's read."""
        tss = getattr(self.cluster, "client_tss", {}).get(server)
        if tss is None:
            return
        self._counter += 1
        if self._counter % TSS_SAMPLE_EVERY:
            return
        self.samples += 1

        async def compare():
            try:
                mirror = await tss.get_value(key, version)
            except Exception:
                # TSS death/slowness is a TSS problem, not a client one
                return
            if mirror != result:
                self.mismatches += 1
                code_probe(True, "tss.mismatch")
                TraceEvent("TSSMismatch", severity=SEV_ERROR).detail(
                    "Key", key
                ).detail("Version", version).detail(
                    "SSValue", result
                ).detail("TSSValue", mirror).detail(
                    "Server", server
                ).log()

        # fire-and-forget by contract (docstring): compare() contains its
        # own errors — a dead TSS must never fail the client's read
        self.sched.spawn(compare(), name=f"tss-compare-{server}")  # flowcheck: ignore[actor.fire-and-forget]
