"""Storage server: versioned MVCC KV store fed from the TLog.

Behavioral mirror of `fdbserver/storageserver.actor.cpp`:

* `update` loop (:9117): pulls its tag's mutations from the TLog in
  version order and applies them to the versioned store.
* The store is the reference's VersionedMap
  (fdbclient/include/fdbclient/VersionedMap.h) in spirit: every key maps
  to its version history within the MVCC window, so a read AT version v
  sees exactly the state as of v — the property that makes read-only
  transactions (which commit client-side without conflict checking)
  serializable. Old versions garbage-collect as the window floor rises.
* Reads (`getValueQ` :2119, `getKeyValuesQ` :4201): wait for the store to
  reach the request version (waitForVersion); reading below the MVCC
  window raises transaction_too_old.
* Shard moves (fetchKeys :7378): while a shard is being fetched, its
  incoming mutations buffer; the snapshot installs at the fetch version
  and the buffer replays above it.

Mutations are ("set", key, value) / ("clear", begin, end) /
("atomic", op, key, param) tuples (MutationRef,
fdbclient/CommitTransaction.h:32-71).
"""

from __future__ import annotations

import bisect
from typing import Any, Optional

from foundationdb_tpu.cluster import sampling as _sampling
from foundationdb_tpu.cluster.tlog import TLog
from foundationdb_tpu.runtime.flow import ActorCancelled, Notified, Scheduler
from foundationdb_tpu.utils import commit_debug as _cd
from foundationdb_tpu.utils import trace as _trace
from foundationdb_tpu.utils.metrics import (
    READ_LATENCY_BANDS,
    LatencyBands,
    LatencySample,
)


class TransactionTooOld(Exception):
    """error_code_transaction_too_old: read below the MVCC window."""


class WrongShardServerError(Exception):
    """error_code_wrong_shard_server: this server no longer owns the
    range (it moved away and the data was dropped). The client
    invalidates its location cache entry and re-resolves
    (fdbclient/NativeAPI.actor.cpp:2969-3097)."""


class StorageServer:
    def __init__(
        self,
        sched: Scheduler,
        tlog: TLog,
        tag: int,
        *,
        recovery_version: int = 0,
        window_versions: int = 5_000_000,
        consumer: str = "storage",
        sample_seed: int = 0,
    ):
        self.sched = sched
        self.tlog = tlog
        self.tag = tag
        # the tlog pop identity: a TSS mirror shares its pair's TAG but
        # must pop under its OWN consumer name, or whichever of the
        # pair pulls first trims messages the other never saw
        # (design/tss.md — the TSS has an independent pop cursor)
        self.consumer = consumer
        if consumer != "storage":
            tlog.register_tag_mirror(tag, consumer)
        self.version = Notified(recovery_version)
        self.durable_version = recovery_version
        self.oldest_version = recovery_version
        self.window_versions = window_versions
        # The versioned store: sorted key list + per-key version history
        # [(version, value-or-None)], ascending; None = cleared.
        self._keys: list[bytes] = []
        self._hist: dict[bytes, list[tuple[int, Optional[bytes]]]] = {}
        # watches: key -> [(expected_value, promise)]
        self._watches: dict[bytes, list] = {}
        # in-progress shard fetches: (begin, end) -> buffered [(v, mutation)]
        self._fetching: dict[tuple, list] = {}
        # shards acquired by a move are only readable from their fetch
        # version: [(begin, end, available_from)] — the reference returns
        # wrong_shard_server for older reads; we raise too-old (both make
        # the client retry at a fresh version)
        self._shard_floors: list[tuple[bytes, bytes, int]] = []
        # ranges this server relinquished (moved away + data dropped):
        # reads there answer wrong_shard_server so a stale client
        # location cache LOUDLY invalidates instead of reading absence
        self._dropped_ranges: list[tuple[bytes, bytes]] = []
        # ownership ceilings: [(begin, end, last_owned_version)] — a
        # leaver set this at the routing flip; reads ABOVE the ceiling
        # must go to the new team (the reference's serverKeys ownership
        # check on the storage, storageserver.actor.cpp) while reads at
        # or below it stay servable until the data actually drops
        self._ceded_ranges: list[tuple[bytes, bytes, int]] = []
        self.stopped = False
        # live (non-cleared) key count, maintained incrementally
        self._live_count = 0
        self._last_gc = recovery_version
        self._update_task = None
        #: fault injection: extra seconds per pull iteration (a slow
        #: disk/IO path; the Ratekeeper must observe the growing lag and
        #: throttle admission — Ratekeeper.actor.cpp's control input)
        self.slowdown = 0.0
        #: fault injection on the READ path: extra seconds per get —
        #: a slow-but-alive replica; the client QueueModel (not the
        #: failure monitor) is what must shed load off it
        self.read_slowdown = 0.0
        # read latency distribution + reference-style bands
        # (storageserver.actor.cpp readLatencyBands), in virtual time
        self.read_latency = LatencySample("readLatency")
        self.read_latency_bands = LatencyBands(
            "ReadLatencyMetrics", READ_LATENCY_BANDS
        )
        # -- saturation sensors (StorageQueueInfo: the Ratekeeper's
        # per-storage inputs — smoothed input bytes, version lag,
        # fetchKeys backlog) — virtual-clock smoothers, deterministic
        # per seed
        from foundationdb_tpu.utils.metrics import Smoother

        self.smoothed_input_bytes = Smoother(1.0, clock=sched.now)
        #: mutations applied by the last pull batch (the apply-queue
        #: depth proxy: a lagging replica catches up in huge batches)
        self.last_batch_mutations = 0
        # -- skew sensors (ISSUE 20): the StorageMetrics byteSample and
        # TransactionTagCounter pair. Seeded from the sim seed (via
        # sample_seed) and clocked off the virtual clock, so every
        # value they surface is bit-deterministic per seed.
        self.byte_sample = _sampling.ByteSample(seed=sample_seed)
        self.read_tags = _sampling.TagCounter(clock=sched.now)
        self.write_tags = _sampling.TagCounter(clock=sched.now)

    def saturation(self) -> dict:
        """The storage server's qos sensor block: how far the apply
        cursor trails the log (apply-queue depth in versions), the
        fetchKeys backlog, and the smoothed write bandwidth. The
        cluster-level version lag (vs the sequencer head) is derived at
        status-assembly time — this process doesn't know the head."""
        return {
            "apply_lag_versions": max(
                0, self.tlog.version.get() - self.version.get()
            ),
            "write_queue_bytes": self.tlog.tag_backlog_bytes(
                self.tag, self.consumer
            ),
            "apply_batch_mutations": self.last_batch_mutations,
            "input_bytes_per_s": self.smoothed_input_bytes.smooth_rate(),
            "fetch_backlog_ranges": len(self._fetching),
            "fetch_backlog_mutations": sum(
                len(buf) for buf in self._fetching.values()
            ),
            "keys": self._live_count,
            "mvcc_window_versions": self.window_versions,
            # -- skew sensors (ISSUE 20): the byteSample estimate, the
            # keyspace heatmap rows and the busiest-tag pair
            "sampled_bytes": self.byte_sample.total_bytes(),
            "sample_keys": self.byte_sample.count,
            "hot_ranges": self.byte_sample.hot_ranges(),
            "busiest_read_tag": self.read_tags.busiest(),
            "busiest_write_tag": self.write_tags.busiest(),
        }

    def start(self) -> None:
        self.stopped = False
        self._update_task = self.sched.spawn(self._update_loop(), name="ss-update")

    def stop(self) -> None:
        self.stopped = True
        if self._update_task is not None:
            self._update_task.cancel()
        if self.consumer != "storage":
            # release the mirror cursor: a dead TSS must not pin its
            # pair's tag retention (code review r5)
            self.tlog.unregister_tag_mirror(self.tag, self.consumer)

    async def ping(self) -> bool:
        """Failure-monitor probe (rides the SimNetwork under simulation,
        so partitions look like death from the monitor's vantage)."""
        return not self.stopped

    # -- write path --------------------------------------------------------

    async def _update_loop(self) -> None:
        try:
            while True:
                if self.slowdown:
                    await self.sched.delay(self.slowdown)
                entries, log_version = await self.tlog.peek(
                    self.tag, self.version.get()
                )
                self.last_batch_mutations = sum(
                    len(msgs) for _v, msgs in entries
                )
                for v, msgs in entries:
                    assert v > self.version.get()
                    for m in msgs:
                        self._ingest(v, m)
                        try:
                            nb = 8 + len(m[1]) + len(m[2])
                        except Exception:
                            nb = 32
                        self.smoothed_input_bytes.add_delta(nb)
                        # busiest-write-tag sensor: the TLog-fed client
                        # write path only (shard-move replays don't
                        # re-count traffic that already counted)
                        key = m[2] if m[0] == "atomic" else m[1]
                        self.write_tags.note(_sampling.tag_of_key(key), nb)
                    self.version.set(v)
                    if _trace.g_trace_batch.enabled:
                        # version-keyed (storage sits below the debug-id
                        # horizon); CommitDebugVersion joins it back to
                        # the committing batch
                        _trace.g_trace_batch.add_event(
                            "CommitDebug", _cd.version_id(v),
                            _cd.STORAGE_APPLIED,
                        )
                # Version leveling: advance to the log's version even when
                # no mutations touched this tag (peek cursor contract).
                if log_version > self.version.get():
                    self.version.set(log_version)
                self.durable_version = self.version.get()
                self._gc(self.durable_version - self.window_versions)
                self.tlog.pop(
                    self.tag, self.durable_version, consumer=self.consumer
                )
                await self.tlog.version.when_at_least(self.version.get() + 1)
        except ActorCancelled:
            raise

    def _ingest(self, v: int, m) -> None:
        """Route one mutation: buffer if its span is being fetched;
        discard if an installed shard's snapshot already covers it."""
        if self._fetching and m[0] == "clear":
            # clears may straddle a fetching range: buffer the clipped
            # overlap for post-install replay AND apply now (the fetching
            # span holds no data yet, so this only affects owned keys).
            for (b, e), buf in self._fetching.items():
                cb, ce = max(m[1], b), min(m[2], e)
                if cb < ce:
                    buf.append((v, ("clear", cb, ce)))
            self._apply_above_floors(v, m)
            return
        rng = self._fetch_range_of(m)
        if rng is not None:
            self._fetching[rng].append((v, m))
        else:
            self._apply_above_floors(v, m)

    def _apply_above_floors(self, v: int, m) -> None:
        """Apply, skipping spans an installed snapshot already covers.

        The update loop's cursor can lag a concurrent install_shard: a
        dual-tagged entry at version <= an installed shard's floor
        arrives AFTER the snapshot (which already reflects it) was
        recorded at the floor version — applying it would write an older
        version on top of a newer one (history out of order; the r5
        2000-seed ensemble, seed 166). Sets/atomics in a floored range
        with v <= floor drop; clears clip to the parts outside such
        ranges."""
        if m[0] != "clear":
            key = m[2] if m[0] == "atomic" else m[1]
            for b, e, floor in self._shard_floors:
                if b <= key < e and v <= floor:
                    return
            self._apply(v, m)
            return
        spans = [(m[1], m[2])]
        for b, e, floor in self._shard_floors:
            if v > floor:
                continue
            nxt = []
            for cb, ce in spans:
                if ce <= b or e <= cb:
                    nxt.append((cb, ce))
                    continue
                if cb < b:
                    nxt.append((cb, b))
                if e < ce:
                    nxt.append((e, ce))
            spans = nxt
        for cb, ce in spans:
            self._apply(v, ("clear", cb, ce))

    def _record(self, v: int, k: bytes, value: Optional[bytes]) -> None:
        if k not in self._hist:
            if value is None:
                return  # clearing a key that never existed
            bisect.insort(self._keys, k)
            self._hist[k] = []
        h = self._hist[k]
        was_live = bool(h) and h[-1][1] is not None
        if h and h[-1][0] == v:
            h[-1] = (v, value)
        else:
            h.append((v, value))
        now_live = value is not None
        self._live_count += int(now_live) - int(was_live)
        # the byteSample tracks the LIVE latest-version state: every
        # state-changing path (client writes, shard installs, drops)
        # funnels through here, so the sample can never drift from the
        # store it estimates
        if now_live:
            self.byte_sample.note_write(k, value)
        else:
            self.byte_sample.erase(k)

    @staticmethod
    def _at_or_below(h: list, v: int) -> int:
        """Index just past the rightmost entry with version <= v.
        (Manual binary search: values may be None, so tuple bisect would
        compare None with bytes.)"""
        lo, hi = 0, len(h)
        while lo < hi:
            mid = (lo + hi) // 2
            if h[mid][0] <= v:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def _value_at(self, k: bytes, v: int) -> Optional[bytes]:
        h = self._hist.get(k)
        if not h:
            return None
        i = self._at_or_below(h, v)
        if i == 0:
            return None
        return h[i - 1][1]

    def _apply(self, v: int, m) -> None:
        kind = m[0]
        if kind == "set":
            self._record(v, m[1], m[2])
            self._fire_watches(m[1])
        elif kind == "atomic":
            from foundationdb_tpu.utils.atomic import apply_atomic

            _, op, k, param = m
            self._record(v, k, apply_atomic(op, self._value_at(k, v), param))
            self._fire_watches(k)
        elif kind == "clear":
            _, b, e = m
            lo = bisect.bisect_left(self._keys, b)
            hi = bisect.bisect_left(self._keys, e)
            for k in self._keys[lo:hi]:
                if self._value_at(k, v) is not None:
                    self._record(v, k, None)
            for k in [k for k in self._watches if b <= k < e]:
                self._fire_watches(k)
        else:
            raise ValueError(f"unknown mutation {m!r}")

    def _gc(self, floor: int) -> None:
        """Raise the MVCC floor: keep one entry at-or-below it per key;
        drop keys whose only state is an old clear. The full-store sweep
        is batched (every ~window/64 of version advance) so steady
        commits don't pay O(all keys) per update tick."""
        if floor <= self.oldest_version:
            return
        self.oldest_version = floor
        if floor - self._last_gc < self.window_versions // 64:
            return
        self._last_gc = floor
        dead = []
        for k, h in self._hist.items():
            i = self._at_or_below(h, floor) - 1
            if i > 0:
                del h[:i]
            if len(h) == 1 and h[0][1] is None and h[0][0] <= floor:
                dead.append(k)
        for k in dead:
            del self._hist[k]
            self._keys.remove(k)

    # -- watches (watchValueSendReply: fire when the value changes) --------

    def watch(self, key: bytes, expected):
        from foundationdb_tpu.runtime.flow import Promise

        p = Promise()
        if self._value_at(key, self.version.get()) != expected:
            p.send(self.version.get())
        else:
            self._watches.setdefault(key, []).append((expected, p))
        return p.future

    def _fire_watches(self, key: bytes) -> None:
        if key not in self._watches:
            return
        current = self._value_at(key, 1 << 62)  # latest, incl. in-apply
        still = []
        for expected, p in self._watches[key]:
            if current != expected:
                p.send(self.version.get())
            else:
                still.append((expected, p))
        if still:
            self._watches[key] = still
        else:
            del self._watches[key]

    # -- shard moves (fetchKeys) ------------------------------------------

    def begin_fetch(self, begin: bytes, end: bytes) -> None:
        self._fetching[(begin, end)] = []

    def install_shard(
        self, begin: bytes, end: bytes,
        items: list[tuple[bytes, bytes]], fetch_version: int,
    ) -> None:
        """Install the fetched snapshot (state as of fetch_version) and
        replay buffered mutations newer than it, in version order. The
        shard is only readable from fetch_version on."""
        buffered = self._fetching.pop((begin, end))
        for k, v in items:
            self._record(fetch_version, k, v)
        for v, m in buffered:
            if v > fetch_version:
                self._apply(v, m)
        self._shard_floors.append((begin, end, fetch_version))
        # re-acquiring a range lifts its wrong_shard_server refusal by
        # SUBTRACTION: a partially overlapping re-acquisition (the
        # balancer moves different range shapes than DD did) must not
        # leave a permanent refusal over keys this server now owns
        # re-acquiring also lifts stale cede ceilings (an aborted move
        # can leave one behind; a current owner must not refuse reads)
        new_ceded: list[tuple[bytes, bytes, int]] = []
        for b, e, ceil_v in self._ceded_ranges:
            if e <= begin or end <= b:
                new_ceded.append((b, e, ceil_v))
                continue
            if b < begin:
                new_ceded.append((b, begin, ceil_v))
            if end < e:
                new_ceded.append((end, e, ceil_v))
        self._ceded_ranges = new_ceded
        new_dropped: list[tuple[bytes, bytes]] = []
        for b, e in self._dropped_ranges:
            if e <= begin or end <= b:
                new_dropped.append((b, e))
                continue
            if b < begin:
                new_dropped.append((b, begin))
            if end < e:
                new_dropped.append((end, e))
        self._dropped_ranges = new_dropped

    def cancel_fetch(self, begin: bytes, end: bytes) -> None:
        """Abort a fetch (move failed before the routing flip): the
        buffered mutations belong to the still-current owner — discard."""
        self._fetching.pop((begin, end), None)

    def cede_shard(self, begin: bytes, end: bytes, version: int) -> None:
        """Ownership of [begin, end) ends at `version`: refuse reads
        above it (WrongShardServerError -> the client re-resolves to the
        new team). Set BEFORE the routing flip — this closes the window
        where a leaver would serve reads at versions whose mutations are
        tagged only to the new team (the r5 2000-seed ensemble's
        lost-write class)."""
        self._ceded_ranges.append((begin, end, version))

    def drop_shard(self, begin: bytes, end: bytes) -> None:
        self._apply(self.version.get(), ("clear", begin, end))
        self._shard_floors = [
            f for f in self._shard_floors
            if not (f[0] >= begin and f[1] <= end)
        ]
        self._ceded_ranges = [
            c for c in self._ceded_ranges
            if not (c[0] >= begin and c[1] <= end)
        ]
        self._dropped_ranges.append((begin, end))

    def _fetch_range_of(self, m):
        if not self._fetching:
            return None
        key = m[2] if m[0] == "atomic" else m[1]
        for (b, e), _buf in self._fetching.items():
            if b <= key < e:
                return (b, e)
        return None

    # -- checkpoint / resume ---------------------------------------------

    def snapshot(self) -> dict:
        """The durable on-disk state a restart recovers from."""
        return {
            "keys": list(self._keys),
            "hist": {k: list(h) for k, h in self._hist.items()},
            "durable_version": self.durable_version,
            "oldest_version": self.oldest_version,
            "live_count": self._live_count,
            "shard_floors": list(self._shard_floors),
            # wrong_shard_server refusals are part of the durable
            # contract: a rebooted server that forgot them would
            # silently serve absence for moved-away ranges to clients
            # holding stale location-cache entries (code-review r4)
            "dropped_ranges": list(self._dropped_ranges),
            "ceded_ranges": list(self._ceded_ranges),
            # the byteSample is durable alongside the store it samples:
            # a rebooted server must not restart skew sensing from an
            # empty (and so wildly underestimating) sample
            "byte_sample": self.byte_sample.snapshot(),
        }

    def restore(self, snap: dict) -> None:
        self._keys = list(snap["keys"])
        self._hist = {k: list(h) for k, h in snap["hist"].items()}
        self.durable_version = snap["durable_version"]
        self.oldest_version = snap["oldest_version"]
        self._live_count = snap["live_count"]
        self._shard_floors = list(snap["shard_floors"])
        self._dropped_ranges = list(snap.get("dropped_ranges", []))
        self._ceded_ranges = list(snap.get("ceded_ranges", []))
        self._last_gc = snap["oldest_version"]
        self.version = Notified(snap["durable_version"])
        if "byte_sample" in snap:
            self.byte_sample.restore(snap["byte_sample"])

    # -- read path -----------------------------------------------------------

    async def _wait_for_version(self, version: int) -> None:
        if version < self.oldest_version:
            raise TransactionTooOld(version)
        await self.version.when_at_least(version)
        if version < self.oldest_version:
            # the MVCC floor can pass the request version DURING the
            # wait: a lagging replica catching up applies a huge version
            # span in one pull batch and GCs history the waiter was
            # about to read — serving now would return a silently
            # PARTIAL state at `version` (keys whose surviving floor
            # entry sits above it vanish). The reference re-validates
            # after waitForVersion for the same reason
            # (storageserver.actor.cpp transaction_too_old). Found by
            # the api workload's model check (soak seeds 1122/1171).
            raise TransactionTooOld(version)

    def _check_shard_floor(self, begin: bytes, end: bytes, version: int) -> None:
        from foundationdb_tpu.cluster.failure_monitor import ProcessFailedError

        if self.stopped:
            # a read reaching a dead process: the transport-level error
            # the client's failure-report fast path consumes
            raise ProcessFailedError(f"storage tag {self.tag} is down")
        for b, e in self._dropped_ranges:
            if begin < e and b < end:
                raise WrongShardServerError((begin, end))
        for b, e, ceiling in self._ceded_ranges:
            if begin < e and b < end and version > ceiling:
                raise WrongShardServerError((begin, end))
        for b, e, floor in self._shard_floors:
            if begin < e and b < end and version < floor:
                # a recently-moved-in shard has no history below its
                # fetch version; the client retries at a fresh version
                raise TransactionTooOld(version)

    async def get_value(self, key: bytes, version: int) -> Optional[bytes]:
        t0 = self.sched.now()
        self._check_shard_floor(key, key + b"\x00", version)  # fail fast
        if self.read_slowdown:
            await self.sched.delay(self.read_slowdown)
        await self._wait_for_version(version)
        self._check_shard_floor(key, key + b"\x00", version)
        dt = self.sched.now() - t0
        self.read_latency.sample(dt)
        self.read_latency_bands.add(dt)
        val = self._value_at(key, version)
        self.read_tags.note(
            _sampling.tag_of_key(key), len(key) + len(val or b"")
        )
        return val

    async def get_key_values(
        self, begin: bytes, end: bytes, version: int, *, limit: int = 1 << 30
    ) -> list[tuple[bytes, bytes]]:
        t0 = self.sched.now()
        self._check_shard_floor(begin, end, version)  # fail fast
        if self.read_slowdown:
            await self.sched.delay(self.read_slowdown)
        await self._wait_for_version(version)
        self._check_shard_floor(begin, end, version)
        dt = self.sched.now() - t0
        self.read_latency.sample(dt)
        self.read_latency_bands.add(dt)
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        out = []
        for k in self._keys[lo:hi]:
            v = self._value_at(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    break
        self.read_tags.note(
            _sampling.tag_of_key(begin),
            sum(len(k) + len(v) for k, v in out) or len(begin),
        )
        return out

    # test/inspection helper: the latest-version view of the data
    @property
    def _data(self) -> dict[bytes, bytes]:
        v = self.version.get()
        return {
            k: val
            for k in self._keys
            if (val := self._value_at(k, v)) is not None
        }
