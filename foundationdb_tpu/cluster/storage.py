"""Storage server: MVCC-windowed versioned KV store fed from the TLog.

Behavioral mirror of `fdbserver/storageserver.actor.cpp`:

* `update` loop (:9117): pulls its tag's mutations from the TLog in
  version order, applies them to the in-memory versioned window, advances
  `version`, then makes them durable and pops the log.
* Reads (`getValueQ` :2119, `getKeyValuesQ` :4201): wait for the store to
  reach the request version (waitForVersion); reading below the MVCC
  window raises transaction_too_old; reads merge the versioned window
  over the durable map at the request version.
* The versioned window is the reference's VersionedMap-over-PTree
  (fdbclient/include/fdbclient/VersionedMap.h) in spirit: here a list of
  (version, mutations) plus a sorted durable dict — O(window) merge reads,
  fine for the simulation scale; the TPU build's hot path is the
  resolver, not storage.

Mutations are ("set", key, value) / ("clear", begin, end) tuples — the
two core MutationRef types (fdbclient/CommitTransaction.h:32-41).
"""

from __future__ import annotations

import bisect
from typing import Any, Optional

from foundationdb_tpu.cluster.tlog import TLog
from foundationdb_tpu.runtime.flow import ActorCancelled, Notified, Scheduler


class TransactionTooOld(Exception):
    """error_code_transaction_too_old: read below the MVCC window."""


class StorageServer:
    def __init__(
        self,
        sched: Scheduler,
        tlog: TLog,
        tag: int,
        *,
        recovery_version: int = 0,
        window_versions: int = 5_000_000,
    ):
        self.sched = sched
        self.tlog = tlog
        self.tag = tag
        self.version = Notified(recovery_version)
        self.durable_version = recovery_version
        self.oldest_version = recovery_version
        self.window_versions = window_versions
        # durable store: sorted key list + dict
        self._keys: list[bytes] = []
        self._data: dict[bytes, bytes] = {}
        # MVCC window: ascending (version, [mutations])
        self._window: list[tuple[int, list[Any]]] = []
        # watches: key -> [(expected_value, promise)]
        self._watches: dict[bytes, list] = {}
        # in-progress shard fetches: (begin, end) -> buffered mutations
        # [(version, mutation)] arriving on our tag before install
        # (the fetchKeys buffer, storageserver.actor.cpp:7378)
        self._fetching: dict[tuple, list] = {}
        self._update_task = None

    def start(self) -> None:
        self._update_task = self.sched.spawn(self._update_loop(), name="ss-update")

    def stop(self) -> None:
        if self._update_task is not None:
            self._update_task.cancel()

    # -- write path --------------------------------------------------------

    async def _update_loop(self) -> None:
        try:
            while True:
                entries, log_version = await self.tlog.peek(
                    self.tag, self.version.get()
                )
                for v, msgs in entries:
                    assert v > self.version.get()
                    self._window.append((v, msgs))
                    self.version.set(v)
                # Version leveling: advance to the log's version even when
                # no mutations touched this tag — commits elsewhere still
                # move every storage server's version forward (the peek
                # cursor contract; storageserver.actor.cpp update loop),
                # otherwise reads at fresh read versions would hang on
                # untouched shards.
                if log_version > self.version.get():
                    self.version.set(log_version)
                # make durable immediately (no disk lag in v0), keep a
                # window of versions for rollback/read-at-version
                self._make_durable(self.version.get())
                # caught up; wait for the log to advance
                await self.tlog.version.when_at_least(self.version.get() + 1)
        except ActorCancelled:
            raise

    def _make_durable(self, up_to: int) -> None:
        for v, msgs in self._window:
            if v > up_to:
                break
            if v <= self.durable_version:
                continue  # already applied
            for m in msgs:
                if m[0] == "clear" and self._fetching:
                    # clears may straddle a fetching range: buffer the
                    # clipped overlap for post-install replay AND apply
                    # the clear now (the fetching span holds no data yet,
                    # so the immediate apply only affects owned keys).
                    for (b, e), buf in self._fetching.items():
                        cb, ce = max(m[1], b), min(m[2], e)
                        if cb < ce:
                            buf.append((v, ("clear", cb, ce)))
                    self._apply_durable(m)
                    continue
                rng = self._fetch_range_of(m)
                if rng is not None:
                    self._fetching[rng].append((v, m))  # buffer until install
                else:
                    self._apply_durable(m)
        self.durable_version = max(self.durable_version, up_to)
        new_oldest = max(self.oldest_version, up_to - self.window_versions)
        self._window = [(v, m) for v, m in self._window if v > new_oldest]
        self.oldest_version = new_oldest
        self.tlog.pop(self.tag, self.durable_version)

    def _apply_durable(self, m) -> None:
        kind = m[0]
        if kind == "set":
            _, k, val = m
            if k not in self._data:
                bisect.insort(self._keys, k)
            self._data[k] = val
            self._fire_watches(k)
        elif kind == "atomic":
            from foundationdb_tpu.utils.atomic import apply_atomic

            _, op, k, param = m
            new = apply_atomic(op, self._data.get(k), param)
            if new is None:
                if k in self._data:
                    del self._data[k]
                    self._keys.remove(k)
            else:
                if k not in self._data:
                    bisect.insort(self._keys, k)
                self._data[k] = new
            self._fire_watches(k)
        elif kind == "clear":
            _, b, e = m
            lo = bisect.bisect_left(self._keys, b)
            hi = bisect.bisect_left(self._keys, e)
            for k in self._keys[lo:hi]:
                del self._data[k]
            del self._keys[lo:hi]
            for k in [k for k in self._watches if b <= k < e]:
                self._fire_watches(k)
        else:
            raise ValueError(f"unknown mutation {m!r}")

    # -- watches (storageserver.actor.cpp watchValueSendReply: fire when
    # the value differs from the watched one) --------------------------------

    def watch(self, key: bytes, expected):
        """Returns a Future firing (with the commit version) once key's
        value != expected."""
        from foundationdb_tpu.runtime.flow import Promise

        p = Promise()
        if self._data.get(key) != expected:
            p.send(self.version.get())  # already different
        else:
            self._watches.setdefault(key, []).append((expected, p))
        return p.future

    def _fire_watches(self, key: bytes) -> None:
        if key not in self._watches:
            return
        current = self._data.get(key)
        still = []
        for expected, p in self._watches[key]:
            if current != expected:
                p.send(self.version.get())
            else:
                still.append((expected, p))
        if still:
            self._watches[key] = still
        else:
            del self._watches[key]

    # -- shard moves (fetchKeys, storageserver.actor.cpp:7378) ------------

    def begin_fetch(self, begin: bytes, end: bytes) -> None:
        """Start receiving a shard: mutations for [begin, end) arriving on
        our tag are buffered until the snapshot is installed."""
        self._fetching[(begin, end)] = []

    def install_shard(
        self, begin: bytes, end: bytes,
        items: list[tuple[bytes, bytes]], fetch_version: int,
    ) -> None:
        """Install the fetched snapshot (taken at fetch_version) and replay
        buffered mutations newer than it, in version order."""
        buffered = self._fetching.pop((begin, end))
        for k, v in items:
            self._apply_durable(("set", k, v))
        for v, m in buffered:
            if v > fetch_version:
                self._apply_durable(m)

    def drop_shard(self, begin: bytes, end: bytes) -> None:
        """Release a moved-away shard's data (MoveKeys cleanup)."""
        self._apply_durable(("clear", begin, end))

    def _fetch_range_of(self, m):
        if not self._fetching:
            return None
        kind = m[0]
        if kind == "set":
            keys = (m[1], m[1])
        elif kind == "atomic":
            keys = (m[2], m[2])
        else:  # clear
            keys = (m[1], m[2])
        for (b, e), _buf in self._fetching.items():
            if kind == "clear":
                if keys[0] < e and b < keys[1]:
                    return (b, e)
            elif b <= keys[0] < e:
                return (b, e)
        return None

    # -- checkpoint / resume ---------------------------------------------

    def snapshot(self) -> dict:
        """The durable on-disk state a restart would recover from
        (storage servers persist at durable_version and replay the log
        tail — storageserver.actor.cpp recovery path)."""
        return {
            "keys": list(self._keys),
            "data": dict(self._data),
            "durable_version": self.durable_version,
        }

    def restore(self, snap: dict) -> None:
        self._keys = list(snap["keys"])
        self._data = dict(snap["data"])
        self.durable_version = snap["durable_version"]
        self.oldest_version = snap["durable_version"]
        self.version = Notified(snap["durable_version"])
        self._window = []

    # -- read path -----------------------------------------------------------

    async def _wait_for_version(self, version: int) -> None:
        if version < self.oldest_version:
            raise TransactionTooOld(version)
        await self.version.when_at_least(version)

    async def get_value(self, key: bytes, version: int) -> Optional[bytes]:
        await self._wait_for_version(version)
        # v0 applies durably as soon as versions arrive, so the durable map
        # already reflects `version`; a lagging-durable design would merge
        # self._window here.
        return self._data.get(key)

    async def get_key_values(
        self, begin: bytes, end: bytes, version: int, *, limit: int = 1 << 30
    ) -> list[tuple[bytes, bytes]]:
        await self._wait_for_version(version)
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        ks = self._keys[lo:hi][:limit]
        return [(k, self._data[k]) for k in ks]
