"""Parallel restore: controller / loaders / appliers.

Capability match for the reference's parallel (a.k.a. "fast") restore
roles — fdbserver/RestoreController.actor.cpp,
RestoreLoader.actor.cpp, RestoreApplier.actor.cpp: instead of one pass
streaming the whole backup through one transaction, the CONTROLLER
partitions the key space into contiguous ranges (one per applier),
LOADERS parse snapshot/log files concurrently and route each mutation
to the applier owning its key range, and APPLIERS apply their shard's
mutations in version order concurrently. Restore time scales with the
applier count instead of the backup size through one pipe.

CLEAR_RANGE mutations spanning applier boundaries are split at the
boundaries (the loader's splitMutation — RestoreLoader.actor.cpp) so
each applier sees exactly its shard's effect.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class RestoreStats:
    snapshot_version: int
    restored_version: int
    appliers: int
    mutations_applied: int
    files_loaded: int


def _partition(boundaries: list[bytes], n: int) -> list[tuple[bytes, bytes]]:
    """n contiguous shards over [b"", b"\\xff") using sampled keys."""
    if n <= 1 or len(boundaries) < n:
        return [(b"", b"\xff")]
    step = len(boundaries) // n
    cuts = [boundaries[i * step] for i in range(1, n)]
    # dedup + ordered
    uniq: list[bytes] = []
    for c in cuts:
        if not uniq or c > uniq[-1]:
            uniq.append(c)
    lo = b""
    shards = []
    for c in uniq:
        shards.append((lo, c))
        lo = c
    shards.append((lo, b"\xff"))
    return shards


class ParallelRestore:
    """Drive a parallel restore of `container` into `db`."""

    def __init__(self, db, container, *, n_appliers: int = 4):
        self.db = db
        self.container = container
        self.n_appliers = n_appliers

    async def run(self, *, target_version: Optional[int] = None) -> RestoreStats:
        from foundationdb_tpu.cluster.backup import select_snapshot

        cont = self.container
        base = select_snapshot(cont, target_version)
        manifest = cont.read_file(f"snapshots/{base:016d}/manifest")
        range_files = [
            f"snapshots/{base:016d}/range_{i:06d}"
            for i in range(manifest["files"])
        ]
        log_files = cont.list_files("logs/")

        # ---- controller: sample keys, cut applier shards ----------------
        # sampled files are cached — the loader pass reads them again,
        # and against an object store every read is a full HTTP GET
        # (code review r5)
        file_cache: dict[str, list] = {}
        sample: list[bytes] = []
        for name in range_files[:: max(1, len(range_files) // 8)]:
            kvs = cont.read_file(name)
            file_cache[name] = kvs
            sample.extend(bytes(k) for k, _v in kvs[:: max(1, len(kvs) // 64)])
        sample.sort()
        shards = _partition(sample, self.n_appliers)

        # ---- loaders: parse files, split + route mutations --------------
        # per-applier: {"kvs": [(k, v)], "logs": {version: [mutation]}}
        plans = [
            {"kvs": [], "logs": {}} for _ in shards
        ]

        def owner(key: bytes) -> int:
            for i, (lo, hi) in enumerate(shards):
                if lo <= key < hi:
                    return i
            return len(shards) - 1

        files_loaded = 0
        restored = base
        for name in range_files:
            files_loaded += 1
            kvs = file_cache.pop(name, None)
            if kvs is None:
                kvs = cont.read_file(name)
            for k, v in kvs:
                k = bytes(k)
                plans[owner(k)]["kvs"].append((k, bytes(v)))
        for name in log_files:
            files_loaded += 1
            for vs, msgs in sorted(cont.read_file(name).items()):
                v = int(vs)
                if v <= base:
                    continue
                if target_version is not None and v > target_version:
                    continue
                restored = max(restored, v)
                for m in msgs:
                    kind = m[0]
                    if kind == "set":
                        i = owner(bytes(m[1]))
                        plans[i]["logs"].setdefault(v, []).append(
                            ("set", bytes(m[1]), bytes(m[2]))
                        )
                    elif kind == "atomic":
                        i = owner(bytes(m[2]))
                        plans[i]["logs"].setdefault(v, []).append(
                            ("atomic", m[1], bytes(m[2]), bytes(m[3]))
                        )
                    elif kind == "clear":
                        # splitMutation: clip the clear at shard bounds
                        cb, ce = bytes(m[1]), bytes(m[2])
                        for i, (lo, hi) in enumerate(shards):
                            b = max(cb, lo)
                            e = min(ce, hi)
                            if b < e:
                                plans[i]["logs"].setdefault(v, []).append(
                                    ("clear", b, e)
                                )

        # ---- appliers: one transaction per shard, concurrent ------------
        # The keyspace clear runs FIRST in its own transaction (the
        # reference clears the restore range before applying).
        txn = self.db.create_transaction()
        txn.clear_range(b"", b"\xff")
        await txn.commit()

        sched = self.db.sched
        applied = [0] * len(shards)

        async def apply_shard(i: int) -> None:
            plan = plans[i]
            txn = self.db.create_transaction()
            for k, v in plan["kvs"]:
                txn.set(k, v)
            for v in sorted(plan["logs"]):
                for m in plan["logs"][v]:
                    if m[0] == "set":
                        txn.set(m[1], m[2])
                    elif m[0] == "clear":
                        txn.clear_range(m[1], m[2])
                    elif m[0] == "atomic":
                        txn.atomic_op(m[1], m[2], m[3])
                    applied[i] += 1
            applied[i] += len(plan["kvs"])
            await txn.commit()

        tasks = [
            sched.spawn(apply_shard(i), name=f"restore-applier-{i}")
            for i in range(len(shards))
        ]
        for t in tasks:
            await t.done

        return RestoreStats(
            snapshot_version=base,
            restored_version=restored,
            appliers=len(shards),
            mutations_applied=sum(applied),
            files_loaded=files_loaded,
        )
