"""Tenants: named, isolated keyspaces.

Behavioral mirror of the reference's tenant support (fdbclient/Tenant.cpp,
TenantManagement.actor.cpp): a tenant is a named prefix allocated in the
system keyspace; transactions opened through a Tenant handle see their
own keyspace (keys transparently prefixed on writes/reads and stripped
on results), and tenant management (create / delete-when-empty / list)
runs as ordinary transactions over `\\xff/tenant/`.
"""

from __future__ import annotations

from typing import Optional

TENANT_MAP_PREFIX = b"\xff/tenant/"
TENANT_COUNTER_KEY = b"\xff/tenantCounter"
TENANT_DATA_PREFIX = b"\x1e"  # allocated tenant prefixes live under this


class TenantExists(Exception):
    pass


class TenantNotFound(Exception):
    pass


class TenantNotEmpty(Exception):
    pass


# -- management (TenantManagement.actor.cpp) -------------------------------


async def create_tenant(db, name: bytes) -> bytes:
    """Allocate and record a tenant; returns its prefix."""
    txn = db.create_transaction()
    key = TENANT_MAP_PREFIX + name
    if await txn.get(key) is not None:
        raise TenantExists(name)
    raw = await txn.get(TENANT_COUNTER_KEY)
    n = int.from_bytes(raw, "little") if raw else 0
    txn.set(TENANT_COUNTER_KEY, (n + 1).to_bytes(8, "little"))
    prefix = TENANT_DATA_PREFIX + n.to_bytes(8, "big")
    txn.set(key, prefix)
    await txn.commit()
    return prefix


async def delete_tenant(db, name: bytes) -> None:
    """Delete a tenant; it must be empty (the reference's invariant)."""
    txn = db.create_transaction()
    key = TENANT_MAP_PREFIX + name
    prefix = await txn.get(key)
    if prefix is None:
        raise TenantNotFound(name)
    if await txn.get_range(prefix, prefix + b"\xff", limit=1):
        raise TenantNotEmpty(name)
    txn.clear(key)
    await txn.commit()


async def list_tenants(db) -> list[bytes]:
    txn = db.create_transaction()
    items = await txn.get_range(TENANT_MAP_PREFIX, TENANT_MAP_PREFIX + b"\xff")
    return [k[len(TENANT_MAP_PREFIX):] for k, _ in items]


# -- the tenant handle -----------------------------------------------------


class Tenant:
    """Database-like handle scoped to one tenant's keyspace.

    With authorization enabled on the cluster (a
    crypto.token_sign.TokenVerifier on cluster.token_verifier), every
    transaction against the tenant requires a signed token granting
    this tenant — the reference's tenant authorization
    (design/authorization.md, fdbrpc/TokenSign): no token, an expired
    one, or one naming other tenants is permission_denied before any
    key resolves."""

    def __init__(self, db, name: bytes, *, token: bytes = None):
        self.db = db
        self.name = name
        self.token = token
        self._prefix: Optional[bytes] = None

    def _authorize(self) -> None:
        verifier = getattr(
            getattr(self.db, "cluster", None), "token_verifier", None
        )
        if verifier is not None:
            # expiry against the SCHEDULER clock, not wall time: under
            # deterministic simulation a wall-clock comparison would
            # make token expiry nondeterministic across re-runs
            verifier.check(self.token, self.name, now=self.db.sched.now())

    async def _resolve(self) -> bytes:
        self._authorize()
        if self._prefix is None:
            txn = self.db.create_transaction()
            prefix = await txn.get(TENANT_MAP_PREFIX + self.name)
            if prefix is None:
                raise TenantNotFound(self.name)
            self._prefix = prefix
        return self._prefix

    def create_transaction(self) -> "TenantTransaction":
        self._authorize()
        return TenantTransaction(self, self.db.create_transaction())

    async def run(self, fn, **kw):
        async def wrapped(txn):
            return await fn(TenantTransaction(self, txn))

        return await self.db.run(wrapped, **kw)


class TenantTransaction:
    """A Transaction whose keys live under the tenant prefix."""

    def __init__(self, tenant: Tenant, txn):
        self._tenant = tenant
        self._txn = txn

    async def _k(self, key: bytes) -> bytes:
        return await self._tenant._resolve() + key

    async def get(self, key: bytes, **kw):
        return await self._txn.get(await self._k(key), **kw)

    async def get_range(self, begin: bytes, end: bytes, **kw):
        p = await self._tenant._resolve()
        items = await self._txn.get_range(p + begin, p + end, **kw)
        return [(k[len(p):], v) for k, v in items]

    async def set(self, key: bytes, value: bytes) -> None:
        self._txn.set(await self._k(key), value)

    async def clear(self, key: bytes) -> None:
        self._txn.clear(await self._k(key))

    async def clear_range(self, begin: bytes, end: bytes) -> None:
        p = await self._tenant._resolve()
        self._txn.clear_range(p + begin, p + end)

    async def atomic_op(self, op: str, key: bytes, param: bytes) -> None:
        self._txn.atomic_op(op, await self._k(key), param)

    async def watch(self, key: bytes):
        return await self._txn.watch(await self._k(key))

    async def commit(self) -> int:
        return await self._txn.commit()

    @property
    def committed_version(self):
        return self._txn.committed_version
