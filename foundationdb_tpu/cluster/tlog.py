"""TLog: the replicated, version-ordered durable mutation log.

Behavioral mirror of `fdbserver/TLogServer.actor.cpp`:

* `commit` (tLogCommit :2311): mutations arrive tagged per storage
  server; versions must arrive in order (prev_version chain); a commit is
  durable once appended (the in-memory deque stands in for the DiskQueue
  ring file — fdbserver/DiskQueue.actor.cpp).
* `peek` (per-tag peek cursors, LogSystemPeekCursor.actor.cpp): a storage
  server reads messages for its tag strictly after a version, blocking
  until the log advances past it.
* `pop` (:popped bookkeeping): once a storage server durably applied a
  version, the prefix can be discarded.

The version chain uses the same Notified pattern as the resolver; commits
with a stale prev_version wait, duplicates are idempotent.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from foundationdb_tpu.runtime.flow import Notified, Scheduler

Tag = int  # storage tag (the reference's Tag{locality, id})


@dataclasses.dataclass
class TLogCommitRequest:
    prev_version: int
    version: int
    # tag -> list of mutations for that storage server
    messages: dict[Tag, list[Any]]
    known_committed_version: int = 0
    epoch: int = 1  # generation of the pushing proxy


#: The full-stream tag: carries each version's COMPLETE ordered mutation
#: list for log-consuming workers (backup/DR) — the role of the
#: reference's dedicated backup mutation tags (BackupWorker.actor.cpp).
#: Emitted by proxies only while such a consumer is registered; retained
#: only for non-storage consumers (storage never reads it).
LOG_STREAM_TAG: Tag = -1


class TLogStoppedError(Exception):
    """error_code_tlog_stopped: a previous-generation push after the log
    was locked by recovery (TagPartitionedLogSystem epoch locking)."""


class TLog:
    """One in-memory tlog instance."""

    def __init__(self, sched: Scheduler, *, recovery_version: int = 0):
        self.sched = sched
        self.epoch = 1
        self.version = Notified(recovery_version)
        # tag -> list of (version, mutations)
        self._messages: dict[Tag, list[tuple[int, list[Any]]]] = {}
        # consumer -> tag -> popped-through version. Messages are retained
        # until EVERY registered consumer has popped them (the reference's
        # per-tag popped bookkeeping generalized to backup workers, which
        # read every tag — fdbserver/BackupWorker.actor.cpp).
        self._popped: dict[str, dict[Tag, int]] = {"storage": {}}

    def lock(self, epoch: int, recovery_version: int = None) -> None:
        """Recovery locks the log to a new generation: pushes from older
        epochs fail from here on (the coordinated-state lock step). When
        the new generation's recovery version is known, the log version
        jumps to it (lastEpochEnd completion) so the first new-epoch push
        (prev_version == recovery_version) can chain."""
        self.epoch = max(self.epoch, epoch)
        if recovery_version is not None and recovery_version > self.version.get():
            self.version.set(recovery_version)

    async def commit(self, req: TLogCommitRequest) -> int:
        """Append one version's messages; returns the durable version."""
        if req.epoch < self.epoch:
            raise TLogStoppedError(f"epoch {req.epoch} < locked {self.epoch}")
        await self.version.when_at_least(req.prev_version)
        if req.epoch < self.epoch:  # may have been locked while waiting
            raise TLogStoppedError(f"epoch {req.epoch} < locked {self.epoch}")
        if self.version.get() >= req.version:
            return self.version.get()  # duplicate (already durable)
        for tag, msgs in req.messages.items():
            self._messages.setdefault(tag, []).append((req.version, msgs))
        self.version.set(req.version)
        return req.version

    async def peek(self, tag: Tag, after_version: int):
        """Messages for `tag` with version > after_version; waits until the
        log has advanced past after_version (peek cursor contract)."""
        await self.version.when_at_least(after_version + 1)
        out = [
            (v, msgs)
            for v, msgs in self._messages.get(tag, [])
            if v > after_version
        ]
        return out, self.version.get()

    def register_consumer(self, name: str) -> None:
        """Retain messages for an extra consumer from this point on."""
        self._popped.setdefault(name, {})

    def has_log_consumers(self) -> bool:
        """Any non-storage consumer registered (proxies emit the
        full-stream tag only when someone will read it)?"""
        return any(name != "storage" for name in self._popped)

    def unregister_consumer(self, name: str) -> None:
        if name != "storage":
            self._popped.pop(name, None)
            for tag in list(self._messages):
                self._trim(tag)

    def pop(self, tag: Tag, up_to_version: int, consumer: str = "storage") -> None:
        """Mark `consumer` done with tag messages <= up_to_version; discard
        what every consumer has popped."""
        marks = self._popped.setdefault(consumer, {})
        marks[tag] = max(marks.get(tag, 0), up_to_version)
        self._trim(tag)

    def _trim(self, tag: Tag) -> None:
        if tag == LOG_STREAM_TAG:
            # storage never pops the full stream; only backup/DR
            # consumers constrain it — none registered = drop everything
            extras = [m for n, m in self._popped.items() if n != "storage"]
            if not extras:
                self._messages[tag] = []
                return
            floor = min(m.get(tag, 0) for m in extras)
        else:
            # per-storage tags are governed by storage ALONE: stream
            # consumers read only LOG_STREAM_TAG, and letting their
            # never-popped marks pin storage tags would leak the whole
            # log for the lifetime of a backup/DR relationship
            floor = self._popped["storage"].get(tag, 0)
        self._messages[tag] = [
            (v, m) for v, m in self._messages.get(tag, []) if v > floor
        ]
