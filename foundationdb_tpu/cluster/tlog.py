"""TLog: the replicated, version-ordered durable mutation log.

Behavioral mirror of `fdbserver/TLogServer.actor.cpp`:

* `commit` (tLogCommit :2311): mutations arrive tagged per storage
  server; versions must arrive in order (prev_version chain); a commit is
  durable once appended (the in-memory deque stands in for the DiskQueue
  ring file — fdbserver/DiskQueue.actor.cpp).
* `peek` (per-tag peek cursors, LogSystemPeekCursor.actor.cpp): a storage
  server reads messages for its tag strictly after a version, blocking
  until the log advances past it.
* `pop` (:popped bookkeeping): once a storage server durably applied a
  version, the prefix can be discarded.

The version chain uses the same Notified pattern as the resolver; commits
with a stale prev_version wait, duplicates are idempotent.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from foundationdb_tpu.runtime.flow import Notified, Scheduler
from foundationdb_tpu.utils.probes import declare

declare("tlog.diskqueue_recovery", "simdisk.torn_tail",
        "tlog.spill", "tlog.peek_from_spill")

Tag = int  # storage tag (the reference's Tag{locality, id})


def _mut_bytes(m) -> int:
    """Cheap per-mutation byte estimate (the queue-bytes sensor's unit;
    MutationRef::expectedSize analog — never exact serialization)."""
    try:
        return 8 + len(m[1]) + len(m[2])
    except Exception:
        return 32


@dataclasses.dataclass
class TLogCommitRequest:
    prev_version: int
    version: int
    # tag -> list of mutations for that storage server
    messages: dict[Tag, list[Any]]
    known_committed_version: int = 0
    epoch: int = 1  # generation of the pushing proxy
    # commit-path telemetry: the pushing batch's debug id + span context
    # (TLogCommitRequest.debugID / spanContext in the reference)
    debug_id: Any = None
    span: Any = None


#: The full-stream tag: carries each version's COMPLETE ordered mutation
#: list for log-consuming workers (backup/DR) — the role of the
#: reference's dedicated backup mutation tags (BackupWorker.actor.cpp).
#: Emitted by proxies only while such a consumer is registered; retained
#: only for non-storage consumers (storage never reads it).
LOG_STREAM_TAG: Tag = -1


class TLogStoppedError(Exception):
    """error_code_tlog_stopped: a previous-generation push after the log
    was locked by recovery (TagPartitionedLogSystem epoch locking)."""


class TLog:
    """One tlog instance.

    With `durable` set (a sim.diskqueue.SimDiskQueue), every commit is
    written-ahead to the queue and "fsynced" before the in-memory state
    updates — the native DiskQueue discipline (native/diskqueue.cpp) on
    the simulated disk, so simulation seeds exercise the recovery scan
    (crash -> restore_from_disk -> peer catch-up) exactly like the
    reference's simulated files reach its DiskQueue code
    (fdbrpc/sim2.actor.cpp simulated disk + AsyncFileNonDurable).
    """

    def __init__(self, sched: Scheduler, *, recovery_version: int = 0,
                 durable=None):
        self.sched = sched
        self.epoch = 1
        self.version = Notified(recovery_version)
        self.dq = durable
        # version -> dq seq of its record (for physical pops)
        self._seq_of_version: list[tuple[int, int]] = []
        # tag -> list of (version, mutations)
        self._messages: dict[Tag, list[tuple[int, list[Any]]]] = {}
        # consumer -> tag -> popped-through version. Messages are retained
        # until EVERY registered consumer has popped them (the reference's
        # per-tag popped bookkeeping generalized to backup workers, which
        # read every tag — fdbserver/BackupWorker.actor.cpp).
        self._popped: dict[str, dict[Tag, int]] = {"storage": {}}
        # TSS mirror consumers per tag (design/tss.md): a mirror reads
        # a STORAGE tag with its own pop cursor — retention for that
        # tag floors at the SLOWEST of the pair, and mirror consumers
        # never constrain LOG_STREAM_TAG (they don't read it; letting
        # their never-popped stream marks pin it would leak the log)
        self._tag_mirrors: dict[Tag, set[str]] = {}
        # SPILL state (TLogServer.actor.cpp:2311 spill-by-reference):
        # when retained mutations exceed SERVER_KNOBS.TLOG_SPILL_THRESHOLD,
        # the OLDEST unpopped versions are evicted from memory and
        # replaced by per-tag (version, dq seq) index entries; peeks for
        # spilled versions read the records back off the DiskQueue. A
        # lagging consumer therefore bounds tlog MEMORY, not disk.
        self._spilled: dict[Tag, list[tuple[int, int]]] = {}
        self._mem_mutations = 0
        # -- saturation sensors (the Ratekeeper's TLogQueueInfo inputs:
        # Ratekeeper.actor.cpp tracks each log's queue bytes through a
        # Smoother before computing the txn/s budget) -----------------
        # retained mutation BYTES, maintained incrementally alongside
        # _mem_mutations (same update sites)
        self._mem_bytes = 0
        from foundationdb_tpu.utils.metrics import Smoother

        #: smoothed retained-queue bytes on the VIRTUAL clock (sim
        #: determinism: identical per seed, safe next to trace digests)
        self.smoothed_queue_bytes = Smoother(1.0, clock=sched.now)
        #: smoothed input bytes/s (the reference's smoothInputBytes)
        self.smoothed_input_bytes = Smoother(1.0, clock=sched.now)

    def saturation(self) -> dict:
        """The tlog's qos sensor block (status JSON `processes.*.qos`):
        retained queue depth/bytes (smoothed + instantaneous) and the
        durability lag — how far the slowest storage pop cursor trails
        this log's version."""
        storage_marks = [
            self._popped["storage"].get(tag, 0)
            for tag in set(self._messages) | set(self._spilled)
            if tag != LOG_STREAM_TAG
        ]
        v = self.version.get()
        return {
            "queue_mutations": self._mem_mutations,
            "queue_bytes": self._mem_bytes,
            "smoothed_queue_bytes": self.smoothed_queue_bytes.smooth_total(),
            "input_bytes_per_s": self.smoothed_input_bytes.smooth_rate(),
            "spilled_versions": sum(
                len(e) for e in self._spilled.values()
            ),
            "durability_lag_versions": (
                v - min(storage_marks) if storage_marks else 0
            ),
        }

    def tag_backlog_bytes(self, tag: Tag, consumer: str = "storage") -> int:
        """Bytes this log still retains for one consumer's tag — the
        per-storage write-queue depth (the reference's storage queue =
        bytesInput - bytesDurable, measured here at the log because the
        sim storage applies synchronously once it pulls). Spilled
        versions count at the estimate used when they were spilled."""
        mark = self._popped.get(consumer, {}).get(tag, 0)
        n = sum(
            _mut_bytes(m)
            for v, msgs in self._messages.get(tag, [])
            if v > mark
            for m in msgs
        )
        # spilled entries carry no byte estimate; charge a flat floor
        # per spilled VERSION entry so the backlog never reads as zero
        n += 32 * sum(
            1 for v, _seq in self._spilled.get(tag, []) if v > mark
        )
        return n

    def lock(self, epoch: int, recovery_version: int = None) -> None:
        """Recovery locks the log to a new generation: pushes from older
        epochs fail from here on (the coordinated-state lock step). When
        the new generation's recovery version is known, the log version
        jumps to it (lastEpochEnd completion) so the first new-epoch push
        (prev_version == recovery_version) can chain."""
        self.epoch = max(self.epoch, epoch)
        if recovery_version is not None and recovery_version > self.version.get():
            self.version.set(recovery_version)

    async def commit(self, req: TLogCommitRequest) -> int:
        """Append one version's messages; returns the durable version."""
        from foundationdb_tpu.utils import commit_debug as _cd
        from foundationdb_tpu.utils import trace as _trace

        if req.epoch < self.epoch:
            raise TLogStoppedError(f"epoch {req.epoch} < locked {self.epoch}")
        if req.debug_id is not None:
            _trace.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cd.TLOG_BEFORE_WAIT
            )
        await self.version.when_at_least(req.prev_version)
        if req.epoch < self.epoch:  # may have been locked while waiting
            raise TLogStoppedError(f"epoch {req.epoch} < locked {self.epoch}")
        if self.version.get() >= req.version:
            return self.version.get()  # duplicate (already durable)
        if self.dq is not None:
            # write-ahead + "fsync" BEFORE the in-memory apply: the ack
            # this commit produces must imply durability (the DiskQueue
            # commit-before-ack contract)
            import pickle

            seq = self.dq.push(
                pickle.dumps((req.prev_version, req.version, req.messages))
            )
            self.dq.commit()
            self._seq_of_version.append((req.version, seq))
        for tag, msgs in req.messages.items():
            self._messages.setdefault(tag, []).append((req.version, msgs))
            self._mem_mutations += len(msgs)
            nb = sum(_mut_bytes(m) for m in msgs)
            self._mem_bytes += nb
            self.smoothed_input_bytes.add_delta(nb)
        self.smoothed_queue_bytes.set_total(self._mem_bytes)
        self.version.set(req.version)
        if req.debug_id is not None:
            _trace.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cd.TLOG_AFTER_COMMIT
            )
        self._maybe_spill()
        return req.version

    def _maybe_spill(self) -> None:
        """Evict the oldest unpopped versions from memory once the
        retained-mutation budget is exceeded; their DiskQueue records
        (already durable — commit fsyncs before the in-memory apply)
        become the backing store, indexed per tag."""
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS
        from foundationdb_tpu.utils.probes import code_probe

        budget = SERVER_KNOBS.TLOG_SPILL_THRESHOLD
        if self.dq is None or self._mem_mutations <= budget:
            return
        seq_of = dict(self._seq_of_version)
        # Pick the eviction set FIRST (oldest versions until back under
        # budget), then partition each tag's list in ONE pass — the
        # per-version rescan of every tag was quadratic in backlog under
        # the sim's randomized small thresholds (code-review r4).
        ver_sizes: dict[int, int] = {}
        for entries in self._messages.values():
            for v, msgs in entries:
                ver_sizes[v] = ver_sizes.get(v, 0) + len(msgs)
        evict: set[int] = set()
        mem = self._mem_mutations
        for v in sorted(ver_sizes):
            if mem <= budget:
                break
            if v not in seq_of:
                continue  # not individually addressable — keep in memory
            evict.add(v)
            mem -= ver_sizes[v]
        if not evict:
            return
        code_probe(True, "tlog.spill")
        for tag in list(self._messages):
            kept = []
            for ev, msgs in self._messages[tag]:
                if ev in evict:
                    self._spilled.setdefault(tag, []).append(
                        (ev, seq_of[ev])
                    )
                    self._mem_mutations -= len(msgs)
                    self._mem_bytes -= sum(_mut_bytes(m) for m in msgs)
                else:
                    kept.append((ev, msgs))
            self._messages[tag] = kept
        self.smoothed_queue_bytes.set_total(self._mem_bytes)

    def _entries_for(self, tag: Tag, after_version: int):
        """Merged (version, msgs) view of a tag: spilled versions read
        back off the DiskQueue + in-memory tail, version-ascending."""
        import pickle

        from foundationdb_tpu.utils.probes import code_probe

        out = []
        for v, seq in self._spilled.get(tag, []):
            if v > after_version:
                code_probe(True, "tlog.peek_from_spill")
                _prev, _v, messages = pickle.loads(self.dq.read(seq))
                out.append((v, messages.get(tag, [])))
        out.extend(
            (v, msgs)
            for v, msgs in self._messages.get(tag, [])
            if v > after_version
        )
        out.sort(key=lambda e: e[0])
        return out

    async def peek(self, tag: Tag, after_version: int):
        """Messages for `tag` with version > after_version; waits until the
        log has advanced past after_version (peek cursor contract).
        Spilled versions are read back off the DiskQueue transparently
        (peekMessagesFromDisk)."""
        await self.version.when_at_least(after_version + 1)
        return self._entries_for(tag, after_version), self.version.get()

    def register_consumer(self, name: str) -> None:
        """Retain messages for an extra consumer from this point on."""
        self._popped.setdefault(name, {})

    def register_tag_mirror(self, tag: Tag, name: str) -> None:
        """A TSS pair: `name` reads `tag` like a storage server with an
        independent pop cursor (design/tss.md)."""
        self._tag_mirrors.setdefault(tag, set()).add(name)
        self._popped.setdefault(name, {})

    def unregister_tag_mirror(self, tag: Tag, name: str) -> None:
        """A dead TSS must release its cursor, or its frozen pop mark
        pins the pair's tag retention forever (code review r5)."""
        mirrors = self._tag_mirrors.get(tag)
        if mirrors is not None:
            mirrors.discard(name)
            if not mirrors:
                del self._tag_mirrors[tag]
        self._popped.pop(name, None)
        self._trim(tag)

    def has_log_consumers(self) -> bool:
        """Any non-storage STREAM consumer registered (proxies emit the
        full-stream tag only when someone will read it)? TSS mirrors
        read storage tags only — counting them would make proxies emit
        a stream nothing pops (unbounded growth; code review r5)."""
        mirror_names = set().union(
            *self._tag_mirrors.values()
        ) if self._tag_mirrors else set()
        return any(
            name != "storage" and name not in mirror_names
            for name in self._popped
        )

    def unregister_consumer(self, name: str) -> None:
        if name != "storage":
            self._popped.pop(name, None)
            for tag in list(self._messages):
                self._trim(tag)

    def pop(self, tag: Tag, up_to_version: int, consumer: str = "storage") -> None:
        """Mark `consumer` done with tag messages <= up_to_version; discard
        what every consumer has popped."""
        marks = self._popped.setdefault(consumer, {})
        marks[tag] = max(marks.get(tag, 0), up_to_version)
        self._trim(tag)
        self._physical_pop()

    def _physical_pop(self) -> None:
        """Discard disk records every consumer is done with: translate
        the min per-tag version floor to a queue sequence number."""
        if self.dq is None or not self._seq_of_version:
            return
        floors = [
            self._popped["storage"].get(tag, 0)
            for tag in set(self._messages) | set(self._spilled)
            if tag != LOG_STREAM_TAG
        ]
        for name, marks in self._popped.items():
            if name != "storage":
                floors.append(min(marks.values()) if marks else 0)
        if not floors:
            return
        floor_v = min(floors)
        last_seq = None
        for v, seq in self._seq_of_version:
            if v <= floor_v:
                last_seq = seq
            else:
                break
        if last_seq is not None:
            # pops are advisory and ride un-fsynced (the reference
            # piggybacks pop locations on the push stream): a crash may
            # lose them, and recovery then replays already-popped
            # records — storage dedups by version, so this is safe AND
            # it gives the ensemble a real lost-unsynced-write path
            self.dq.pop(last_seq + 1)
            self._seq_of_version = [
                (v, s) for v, s in self._seq_of_version if v > floor_v
            ]

    def restore_from_disk(self) -> None:
        """The recovery scan: rebuild state from the durable queue after
        a crash (records above the popped floor, version-ascending)."""
        import pickle

        from foundationdb_tpu.utils.probes import code_probe

        code_probe(True, "tlog.diskqueue_recovery")
        assert self.dq is not None
        self._messages = {}
        self._spilled = {}
        self._mem_mutations = 0
        self._mem_bytes = 0
        self._seq_of_version = []
        last_version = 0
        for seq, blob in self.dq.recovered:
            _prev, v, messages = pickle.loads(blob)
            if v <= last_version:
                continue  # duplicate record
            for tag, msgs in messages.items():
                self._messages.setdefault(tag, []).append((v, msgs))
                self._mem_mutations += len(msgs)
                self._mem_bytes += sum(_mut_bytes(m) for m in msgs)
            self._seq_of_version.append((v, seq))
            last_version = v
        self.smoothed_queue_bytes.set_total(self._mem_bytes)
        self._maybe_spill()  # a big recovered tail re-spills immediately
        if last_version > self.version.get():
            self.version.set(last_version)

    def catch_up_from(self, peer: "TLog") -> None:
        """Copy versions the peer has above ours (the rebooted replica
        missed pushes while dead; in the reference the new generation's
        logs recover the old generation's tail the same way). The copied
        versions are written through OUR durable queue too — otherwise a
        second crash would lose acked versions the first recovery only
        held in memory."""
        import pickle

        my_v = self.version.get()
        copied: dict[int, dict] = {}
        # the peer's merged view: spilled versions come back off its
        # DiskQueue (a catch-up must not miss what the peer evicted)
        for tag in set(peer._messages) | set(peer._spilled):
            for v, msgs in peer._entries_for(tag, my_v):
                self._messages.setdefault(tag, []).append((v, msgs))
                self._mem_mutations += len(msgs)
                self._mem_bytes += sum(_mut_bytes(m) for m in msgs)
                copied.setdefault(v, {})[tag] = msgs
        for tag in self._messages:
            self._messages[tag].sort(key=lambda e: e[0])
        if self.dq is not None:
            for v in sorted(copied):
                seq = self.dq.push(pickle.dumps((my_v, v, copied[v])))
                self._seq_of_version.append((v, seq))
            self._seq_of_version.sort(key=lambda e: e[0])
            self.dq.commit()
        if peer.version.get() > self.version.get():
            self.version.set(peer.version.get())
        self.epoch = peer.epoch
        # adopt the peer's pop bookkeeping (ours died with the process)
        self._popped = {
            n: dict(m) for n, m in peer._popped.items()
        }
        self.smoothed_queue_bytes.set_total(self._mem_bytes)
        self._maybe_spill()  # the copied tail respects the memory budget

    def _trim(self, tag: Tag) -> None:
        if tag == LOG_STREAM_TAG:
            # storage never pops the full stream; only backup/DR
            # consumers constrain it — none registered = drop everything
            # (TSS mirrors read storage tags only, never the stream)
            mirror_names = set().union(
                *self._tag_mirrors.values()
            ) if self._tag_mirrors else set()
            extras = [
                m for n, m in self._popped.items()
                if n != "storage" and n not in mirror_names
            ]
            if not extras:
                self._mem_mutations -= sum(
                    len(m) for _v, m in self._messages.get(tag, [])
                )
                self._mem_bytes -= sum(
                    _mut_bytes(m)
                    for _v, ms in self._messages.get(tag, [])
                    for m in ms
                )
                self._messages[tag] = []
                self._spilled.pop(tag, None)
                self.smoothed_queue_bytes.set_total(self._mem_bytes)
                return
            floor = min(m.get(tag, 0) for m in extras)
        else:
            # per-storage tags are governed by storage ALONE (stream
            # consumers read only LOG_STREAM_TAG, and letting their
            # never-popped marks pin storage tags would leak the whole
            # log for the lifetime of a backup/DR relationship) — plus
            # any TSS mirror of the tag: the pair's SLOWEST cursor
            floor = self._popped["storage"].get(tag, 0)
            for m in self._tag_mirrors.get(tag, ()):
                floor = min(floor, self._popped.get(m, {}).get(tag, 0))
        dropped = [
            (v, m) for v, m in self._messages.get(tag, []) if v <= floor
        ]
        self._mem_mutations -= sum(len(m) for _v, m in dropped)
        self._mem_bytes -= sum(
            _mut_bytes(m) for _v, ms in dropped for m in ms
        )
        self.smoothed_queue_bytes.set_total(self._mem_bytes)
        self._messages[tag] = [
            (v, m) for v, m in self._messages.get(tag, []) if v > floor
        ]
        if tag in self._spilled:
            self._spilled[tag] = [
                (v, s) for v, s in self._spilled[tag] if v > floor
            ]
