"""Backup and restore: snapshot + mutation-log backup into a container.

Behavioral mirror of the reference's backup stack in miniature
(fdbclient/FileBackupAgent.actor.cpp + BackupContainer*.cpp +
fdbserver/BackupWorker.actor.cpp): a backup is (a) a range snapshot of
the keyspace at a version, written as range files, plus (b) a continuous
mutation log pulled from the TLog, written as log files; restore loads
the newest snapshot at-or-below the target version and replays the
mutation log up to it. Containers abstract the storage medium (the
reference's file/S3/azure backends): here an in-memory dict container
and a local-directory container (JSON files).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional


class BackupContainer:
    """In-memory container (the IBackupContainer shape)."""

    def __init__(self):
        self.files: dict[str, Any] = {}

    def write_file(self, name: str, data) -> None:
        self.files[name] = data

    def read_file(self, name: str):
        return self.files[name]

    def list_files(self, prefix: str = "") -> list[str]:
        return sorted(n for n in self.files if n.startswith(prefix))


class DirBackupContainer(BackupContainer):
    """Local-directory container (file:// URLs in the reference)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def write_file(self, name: str, data) -> None:
        full = os.path.join(self.path, name)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "w") as f:
            json.dump(_jsonable(data), f)

    def read_file(self, name: str):
        with open(os.path.join(self.path, name)) as f:
            return _unjsonable(json.load(f))

    def list_files(self, prefix: str = "") -> list[str]:
        out = []
        for root, _dirs, files in os.walk(self.path):
            for fn in files:
                rel = os.path.relpath(os.path.join(root, fn), self.path)
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


def _jsonable(x):
    if isinstance(x, bytes):
        return {"__b": x.decode("latin-1")}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    return x


def _unjsonable(x):
    if isinstance(x, dict):
        if set(x) == {"__b"}:
            return x["__b"].encode("latin-1")
        return {k: _unjsonable(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_unjsonable(v) for v in x]
    return x


def select_snapshot(container, target_version=None) -> int:
    """Newest snapshot at-or-below target (shared by the sequential
    and parallel restore paths — one eligibility rule, not two)."""
    snaps = [
        int(n.split("/")[1])
        for n in container.list_files("snapshots/")
        if n.endswith("/manifest")
    ]
    if not snaps:
        raise ValueError("container has no snapshots")
    eligible = [
        v for v in snaps if target_version is None or v <= target_version
    ]
    if not eligible:
        raise ValueError(
            f"no snapshot at or below target version {target_version}"
        )
    return max(eligible)


class BackupAgent:
    """Drives snapshot + log backup against a live cluster."""

    def __init__(self, db, container: BackupContainer):
        self.db = db
        self.container = container
        self._manager = None
        self.log_version = 0

    # -- snapshot (range files; FileBackupAgent range tasks) ---------------

    def register_log_consumer(self, cluster) -> None:
        """Must precede (or coincide with) the snapshot: proxies emit the
        full-stream tag only while a log consumer is registered, so a
        mutation between the snapshot's read version and registration
        would otherwise be on neither the snapshot nor the stream."""
        cluster.tlog.register_consumer("backup")
        self._tlog = cluster.tlog

    async def _stream_barrier(self, cluster) -> None:
        """Close the registration race: a batch IN FLIGHT when the
        consumer registers may have assigned its tags pre-registration
        while committing ABOVE the snapshot's read version — on neither
        the snapshot nor the stream (found by the soak's
        BackupToDBCorrectness workload, seed 6). Each proxy's pipeline
        assigns batches serially, so one barrier commit PER PROXY after
        registration guarantees every later-version batch on that proxy
        emits the stream tag; the snapshot read version, taken after
        the barriers, then covers everything that didn't. The reference
        gets the same fence from writing the backup config through a
        transaction the proxies apply at a version."""
        # Fence EACH proxy that existed at registration with a PINNED
        # commit — round-robin adjacency is broken by concurrent
        # traffic (second review pass). A proxy replaced by recovery
        # needs no fence: post-registration proxies see the consumer
        # from their first batch.
        fence_set = list(getattr(cluster, "commit_proxies", []))
        for i, proxy in enumerate(fence_set):
            last = None
            for _attempt in range(60):
                if proxy not in getattr(cluster, "commit_proxies", []):
                    break  # replaced by a post-registration generation
                txn = self.db.create_transaction()
                txn.set(b"\xff/backup/barrier", b"%d" % i)
                txn._pin_proxy = proxy
                try:
                    await txn.commit()
                    break
                except Exception as e:
                    last = e
                    await self.db.sched.delay(0.02)
            else:
                # permanent failure (e.g. a LOCKED DR destination) must
                # surface, not hang the snapshot forever (code review r5)
                raise last if last is not None else RuntimeError(
                    "stream barrier could not commit"
                )

    async def snapshot(self, *, chunk: int = 1000) -> int:
        """Full range snapshot at one read version; returns that version."""
        cluster = getattr(self.db, "cluster", None)
        if cluster is not None:
            self.register_log_consumer(cluster)
            await self._stream_barrier(cluster)
        txn = self.db.create_transaction()
        version = await txn.get_read_version()
        items = await txn.get_range(b"", b"\xff")
        for i in range(0, max(len(items), 1), chunk):
            part = items[i : i + chunk]
            self.container.write_file(
                f"snapshots/{version:016d}/range_{i // chunk:06d}",
                [[k, v] for k, v in part],
            )
        self.container.write_file(
            f"snapshots/{version:016d}/manifest",
            {"version": version, "files": (len(items) + chunk - 1) // chunk},
        )
        return version

    # -- continuous mutation log (BackupWorker roles) ---------------------

    def start_log_backup(self, cluster) -> None:
        """Recruit per-epoch BackupWorkers (cluster/backup_worker.py):
        the full-stream tag — every committed mutation exactly once, in
        commit order — flows into log files, and recoveries hand off
        between workers with chained watermarks (the reference's
        BackupWorker displacement discipline)."""
        from foundationdb_tpu.cluster.backup_worker import (
            BackupWorkerManager,
        )

        self.register_log_consumer(cluster)
        self._manager = BackupWorkerManager(
            self.db.sched, lambda: cluster, self.container,
            start_version=self.log_version,
        )
        self._manager.start()

    def stop_log_backup(self) -> None:
        if self._manager is not None:
            self.log_version = self._manager.saved_version
            if self._manager.worker is not None:
                self.log_version = max(
                    self.log_version, self._manager.worker.saved_version
                )
            self._manager.stop()  # owns the consumer registration
            self._manager = None

    # -- restore (parallel-restore roles, compressed to one pass) ----------

    async def restore(self, *, target_version: Optional[int] = None) -> int:
        """Clear the keyspace and restore snapshot + logs up to target."""
        base = select_snapshot(self.container, target_version)
        manifest = self.container.read_file(f"snapshots/{base:016d}/manifest")

        txn = self.db.create_transaction()
        txn.clear_range(b"", b"\xff")
        for i in range(manifest["files"]):
            for k, v in self.container.read_file(
                f"snapshots/{base:016d}/range_{i:06d}"
            ):
                txn.set(bytes(k), bytes(v))
        # replay mutation log (base, target]
        restored = base
        for name in self.container.list_files("logs/"):
            for vs, msgs in sorted(self.container.read_file(name).items()):
                v = int(vs)
                if v <= base:
                    continue
                if target_version is not None and v > target_version:
                    continue
                for m in msgs:
                    kind = m[0]
                    if kind == "set":
                        txn.set(bytes(m[1]), bytes(m[2]))
                    elif kind == "clear":
                        txn.clear_range(bytes(m[1]), bytes(m[2]))
                    elif kind == "atomic":
                        txn.atomic_op(m[1], bytes(m[2]), bytes(m[3]))
                restored = max(restored, v)
        await txn.commit()
        return restored
