"""Coordination quorum: generation-based CoordinatedState + leader election.

The role of `fdbserver/Coordination.actor.cpp:864` (coordinationServer),
`CoordinatedState.actor.cpp`, and `LeaderElection.actor.cpp`: N small
replicated registers whose generation protocol makes cluster recovery
safe across real failures — a new ClusterController can only take over by
writing through a MAJORITY of coordinators with a generation strictly
above anything previously seen, so two generations can never both think
they own the cluster, and the cluster survives any minority of
coordinators dying.

Protocol (the reference's two-phase generation discipline):

* Each coordinator holds `(read_gen, write_gen, value)`.
* **Phase 1 (lock)**: the client picks a candidate generation above every
  generation it has seen and asks a majority to raise `read_gen` to it; a
  coordinator refuses if it already promised a higher read_gen. The
  replies carry each coordinator's current `(write_gen, value)`; the
  client adopts the value with the highest write_gen — the one a prior
  writer may have committed through a majority.
* **Phase 2 (write)**: the client writes `(value, gen)` to a majority;
  a coordinator refuses if its read_gen moved past the client's gen.
  Success means any later generation's phase 1 will see this value.

Leader election rides on it: candidates CAS themselves in with a lease;
the recovery epoch lock is a CoordinatedState write, so a deposed CC's
epoch bump fails loudly (the `CoordinatorsChangedError`/stale-generation
path in the reference).

Everything runs on the deterministic simulator's scheduler, so quorum
races are reproducible per seed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from foundationdb_tpu.runtime.flow import Scheduler
from foundationdb_tpu.utils.probes import code_probe, declare
from foundationdb_tpu.utils.trace import TraceEvent

declare(
    "coordination.stale_generation",
    "coordination.quorum_unreachable",
    "coordination.racing_writer_detected",
)


class CoordinatorDead(Exception):
    """This coordinator process is down; requests fail."""


class QuorumUnreachable(Exception):
    """Fewer than a majority of coordinators answered."""


class StaleGeneration(Exception):
    """A higher generation was seen; this client must retry or yield.

    Carries the highest promised generation so the refused client can
    advance its own counter (the reference clients learn generations from
    refusals the same way)."""

    def __init__(self, msg: str, promised: "Generation" = None):
        super().__init__(msg)
        self.promised = promised


@dataclasses.dataclass(order=True)
class Generation:
    """Totally ordered (count, client_id) — unique per attempt."""

    count: int = 0
    client_id: str = ""


class Coordinator:
    """One coordinator: a generation-guarded register (+ leader lease).

    The per-process state `coordinationServer` keeps in its OnDemandStore;
    `kill()`/`revive()` are the fault-injection hooks.
    """

    def __init__(self, name: str):
        self.name = name
        self.read_gen = Generation()
        self.write_gen = Generation()
        self.value: Any = None
        self.alive = True

    # -- fault injection -------------------------------------------------

    def kill(self) -> None:
        self.alive = False

    def revive(self) -> None:
        # state survives (on-disk in the reference); only liveness toggles
        self.alive = True

    def _check(self) -> None:
        if not self.alive:
            raise CoordinatorDead(self.name)

    # -- the generation protocol (server side) ---------------------------

    async def lock(self, gen: Generation):
        """Phase 1: promise not to accept writes below `gen`."""
        self._check()
        if gen < self.read_gen:
            raise StaleGeneration(
                f"{self.name}: promised {self.read_gen}", self.read_gen
            )
        self.read_gen = gen
        return (self.write_gen, self.value)

    async def write(self, gen: Generation, value: Any):
        """Phase 2: accept iff no higher generation was promised."""
        self._check()
        if gen < self.read_gen:
            raise StaleGeneration(
                f"{self.name}: promised {self.read_gen}", self.read_gen
            )
        self.read_gen = gen
        self.write_gen = gen
        self.value = value
        return True


class CoordinatedState:
    """Client driver: majority read/write over the coordinators.

    One instance per logical client (e.g. a would-be cluster controller).
    The reference equivalent is CoordinatedState.actor.cpp's
    read()/setExclusive() pair.
    """

    def __init__(self, sched: Scheduler, coordinators: list[Coordinator],
                 client_id: str):
        self.sched = sched
        self.coordinators = coordinators
        self.client_id = client_id
        self._seen = Generation()
        self._read_wgen = Generation()  # newest write_gen seen by read()

    @property
    def majority(self) -> int:
        return len(self.coordinators) // 2 + 1

    async def _ask_all(self, fn_name: str, *args) -> list:
        """Call fn on every coordinator; collect successes/refusals."""
        oks, stale = [], []
        for c in self.coordinators:
            try:
                oks.append(await getattr(c, fn_name)(*args))
            except CoordinatorDead:
                continue
            except StaleGeneration as e:
                stale.append(e)
        if stale:
            code_probe(True, "coordination.stale_generation")
            # someone promised higher: this client's generation is dead.
            # Adopt the highest promised count so the next attempt can win.
            top = max(
                (e.promised for e in stale if e.promised is not None),
                default=None,
            )
            if top is not None and top.count > self._seen.count:
                self._seen = Generation(top.count, self.client_id)
            raise StaleGeneration(str(stale[0]), top)
        if len(oks) < self.majority:
            code_probe(True, "coordination.quorum_unreachable")
            raise QuorumUnreachable(
                f"{len(oks)}/{len(self.coordinators)} answered"
            )
        return oks

    def _next_gen(self) -> Generation:
        self._seen = Generation(self._seen.count + 1, self.client_id)
        return self._seen

    async def read(self) -> Any:
        """Majority read: lock a fresh generation, adopt the newest value.

        Retries with an advanced counter when refused — a read carries no
        conditional intent, so retrying after a refusal is always safe."""
        for _attempt in range(8):
            gen = self._next_gen()
            try:
                replies = await self._ask_all("lock", gen)
            except StaleGeneration:
                continue  # counter advanced by _ask_all; try again
            best_gen, best_val = Generation(), None
            for wgen, val in replies:
                if wgen >= best_gen and val is not None:
                    best_gen, best_val = wgen, val
            self._read_wgen = best_gen
            return best_val
        raise StaleGeneration("read outran by other clients 8 times")

    async def write(self, value: Any) -> None:
        """Exclusive conditional write: lock, verify nothing was committed
        since our last read(), then commit through a majority — the
        read-modify-write atomicity of the reference's setExclusive.
        Raises StaleGeneration if any higher generation locked OR any
        coordinator committed a value newer than our read (a racing
        client won; caller must re-read the world)."""
        gen = self._next_gen()
        replies = await self._ask_all("lock", gen)
        for wgen, _val in replies:
            # the generation LOCK protects the wait below, not a
            # re-read: once every coordinator holds our gen, a racing
            # writer either lost (lower gen, rejected) or makes OUR
            # write fail StaleGeneration — the reference's setExclusive
            # atomicity argument
            if code_probe(wgen > self._read_wgen,  # flowcheck: ignore[flow.stale-read-across-wait]
                          "coordination.racing_writer_detected"):
                raise StaleGeneration(
                    f"value committed at {wgen} since our read at "
                    f"{self._read_wgen}"
                )
        await self._ask_all("write", gen, value)
        self._read_wgen = gen


@dataclasses.dataclass
class LeaderLease:
    leader: str
    epoch: int
    expires: float  # simulator time


class LeaderElection:
    """Lease-based leader election over CoordinatedState.

    Candidates race to write themselves as the leader; the committed
    write through a majority is the decision (LeaderElection.actor.cpp's
    candidacy). The leader renews its lease; on expiry any candidate may
    take over with a higher epoch. Safety comes from the generation
    protocol: two candidates cannot both commit the same epoch.
    """

    def __init__(self, sched: Scheduler, coordinators: list[Coordinator],
                 candidate_id: str, *, lease: float = 2.0):
        self.sched = sched
        self.cs = CoordinatedState(sched, coordinators, candidate_id)
        self.candidate_id = candidate_id
        self.lease = lease

    async def try_become_leader(self) -> Optional[LeaderLease]:
        """One election attempt; returns the lease if won, None if a live
        leader exists or the attempt was raced out."""
        try:
            cur: Optional[LeaderLease] = await self.cs.read()
            now = self.sched.now()
            if (
                cur is not None
                and cur.leader != self.candidate_id
                and cur.expires > now
            ):
                return None  # live leader elsewhere
            epoch = (cur.epoch if cur else 0) + 1
            lease = LeaderLease(
                leader=self.candidate_id, epoch=epoch,
                expires=now + self.lease,
            )
            await self.cs.write(lease)
            TraceEvent("LeaderElected").detail("Leader", self.candidate_id) \
                .detail("Epoch", epoch).log()
            return lease
        except (StaleGeneration, QuorumUnreachable):
            return None

    async def bump_epoch(self, held: LeaderLease) -> Optional[LeaderLease]:
        """Commit an epoch bump through the quorum while holding the
        lease — the recovery epoch lock (a deposed leader fails here).
        Returns the new lease, or None if leadership was lost."""
        try:
            cur: Optional[LeaderLease] = await self.cs.read()
            if cur is None or cur.leader != self.candidate_id \
                    or cur.epoch != held.epoch:
                return None
            bumped = LeaderLease(
                leader=self.candidate_id, epoch=held.epoch + 1,
                expires=self.sched.now() + self.lease,
            )
            await self.cs.write(bumped)
            return bumped
        except (StaleGeneration, QuorumUnreachable):
            return None

    async def renew(self, held: LeaderLease) -> Optional[LeaderLease]:
        """Extend the lease; None means leadership was lost."""
        try:
            cur: Optional[LeaderLease] = await self.cs.read()
            if cur is None or cur.leader != self.candidate_id \
                    or cur.epoch != held.epoch:
                return None
            renewed = LeaderLease(
                leader=self.candidate_id, epoch=held.epoch,
                expires=self.sched.now() + self.lease,
            )
            await self.cs.write(renewed)
            return renewed
        except (StaleGeneration, QuorumUnreachable):
            return None
