"""Adaptive batch sizing: the dynamic commitBatcher feedback controller.

Behavioral mirror of the reference's CommitProxy batching policy
(fdbserver/CommitProxyServer.actor.cpp:361 `commitBatcher` +
ServerKnobs COMMIT_TRANSACTION_BATCH_*): batches are bounded by a
count target, a bytes target and an accumulation interval, and all
three MOVE with load instead of being fixed knobs:

* the **interval tracks the measured downstream stage latency**
  (resolve + tlog-push seconds per batch) at the reference's
  COMMIT_TRANSACTION_BATCH_INTERVAL_LATENCY_FRACTION, clamped by the
  MIN/MAX knobs: a slow stage (e.g. a kernel resolver's fixed
  per-dispatch cost) earns a longer accumulation window — bigger
  batches amortize the dispatch — while a fast pipeline shrinks the
  window back for low-latency dispatch. Before any latency is
  observed, full batches shrink the window and underfull interval-
  expiry dispatches relax it (the idle/cold-start heuristic).
* the **count/bytes targets grow on evidence** (a batch that filled to
  target and still finished under the latency budget shows headroom),
  capped by the *_MAX knobs.

The controller is deterministic (pure arithmetic over observed
latencies — virtual time under simulation, wall clock on the wire) and
shared by the in-process CommitProxy, the GRV proxy and the
multiprocess wire ProxyPipeline.
"""

from __future__ import annotations


class AdaptiveBatchSizer:
    """Feedback-controlled (interval, count target, bytes target)."""

    def __init__(
        self,
        *,
        interval: float,
        min_interval: float,
        max_interval: float,
        target_count: int,
        max_count: int,
        target_bytes: int = 1 << 20,
        max_bytes: int = 8 << 20,
        latency_budget: float = 0.1,
        alpha: float = 0.1,
        latency_fraction: float = 0.1,
    ):
        self.interval = min(max(interval, min_interval), max_interval)
        self.min_interval = min_interval
        self.max_interval = max_interval
        self.target_count = max(1, min(target_count, max_count))
        self.max_count = max_count
        self.target_bytes = min(target_bytes, max_bytes)
        self.max_bytes = max_bytes
        self.latency_budget = latency_budget
        self.alpha = alpha
        #: the reference's COMMIT_TRANSACTION_BATCH_INTERVAL_LATENCY_
        #: FRACTION: once stage latency is observed, the accumulation
        #: interval TRACKS fraction * smoothed latency (clamped by the
        #: MIN/MAX knobs) — a slow downstream stage (e.g. a fixed
        #: per-dispatch kernel cost) earns BIGGER batches, never a
        #: frantic cadence of tiny ones
        self.latency_fraction = latency_fraction
        #: smoothed resolve+log seconds per batch (None until observed)
        self.smoothed_stage_latency: float | None = None

    # -- dispatch-side feedback (called by the batcher) -------------------

    def batch_full(self) -> None:
        """A batch hit its count/bytes target before the interval
        expired: traffic outruns the dispatch cadence — shrink the
        accumulation window (the reference's interval *= 1-SMOOTHER).
        Once stage latency is flowing, the latency fraction owns the
        interval (observe_stage_latency) and this is a no-op."""
        if self.smoothed_stage_latency is None:
            self.interval = max(
                self.min_interval, self.interval * (1.0 - self.alpha)
            )

    def batch_underfull(self, n_txns: int) -> None:
        """A batch went out on interval expiry well under target: relax
        the window back toward the MAX knob so idle periods don't keep
        paying the loaded cadence. No-op once the latency signal owns
        the interval (see batch_full)."""
        if (
            self.smoothed_stage_latency is None
            and n_txns * 2 <= self.target_count
        ):
            self.interval = min(
                self.max_interval, self.interval * (1.0 + self.alpha / 2)
            )

    # -- completion-side feedback (called when a batch finishes) ----------

    def observe_stage_latency(self, seconds: float, *, full: bool) -> None:
        """Feed back one batch's measured resolve+log stage seconds.

        The interval follows the reference's latency-fraction rule:
        interval = clamp(LATENCY_FRACTION * smoothed stage seconds).
        High downstream latency means each dispatch carries a fixed
        cost worth amortizing — the window grows (toward the MAX knob)
        so batches get bigger; a fast pipeline shrinks the window back
        toward the MIN knob for low-latency dispatch.

        Count/bytes targets only GROW (toward the *_MAX knobs), and
        only on evidence: a batch that filled to target AND finished
        under budget shows headroom at the current size (`full` = the
        batch had reached its count/bytes target — an underfull batch
        finishing fast says nothing about headroom)."""
        s = self.smoothed_stage_latency
        self.smoothed_stage_latency = (
            seconds if s is None else s * (1.0 - self.alpha) + seconds * self.alpha
        )
        lat = self.smoothed_stage_latency
        self.interval = min(
            self.max_interval,
            max(self.min_interval, self.latency_fraction * lat),
        )
        if full and lat < self.latency_budget:
            self.target_count = min(
                self.max_count, max(self.target_count + 1,
                                    int(self.target_count * 1.1))
            )
            self.target_bytes = min(
                self.max_bytes, int(self.target_bytes * 1.1)
            )

    def as_dict(self) -> dict:
        return {
            "interval": self.interval,
            "target_count": self.target_count,
            "target_bytes": self.target_bytes,
            "smoothed_stage_latency": self.smoothed_stage_latency,
        }


def commit_txn_bytes(txn) -> int:
    """Cheap wire-size estimate of one CommitTransaction: conflict-range
    keys + mutation params + fixed per-field overhead. Used for the
    bytes target only — never exact serialization length."""
    n = 64
    for b, e in txn.read_conflict_ranges:
        n += 8 + len(b) + len(e)
    for b, e in txn.write_conflict_ranges:
        n += 8 + len(b) + len(e)
    for m in txn.mutations:
        if isinstance(m, tuple):
            for part in m[1:]:
                n += 5 + (len(part) if isinstance(part, bytes) else 8)
        else:
            n += 9 + len(m.param1) + len(m.param2)
    return n
