"""Multi-region skeleton: a remote DC fed by a log router, with failover.

The reference's multi-region HA (fdbserver/TagPartitionedLogSystem.actor.cpp
+ fdbserver/LogRouter.actor.cpp + documentation/sphinx/source/
ha-write-path.rst): the primary region commits as usual; LOG ROUTERS pull
the primary logs' mutation stream and feed the remote region's logs,
whose storage servers apply asynchronously — the remote trails by a
bounded version lag and can take over when the primary dies.

This skeleton keeps those moving parts and their contracts:

* `LogRouter` registers as a full-stream consumer on the PRIMARY log
  system (the same retained-stream mechanism backup/DR workers use,
  cluster/tlog.py LOG_STREAM_TAG) and pushes each version into the
  REMOTE LogSystem as an ordinary version-chained commit. Remote
  storage servers then pull the remote logs exactly like primary ones
  pull theirs — one storage implementation, both regions.
* `RemoteDC.lag()` reports the version distance primary -> remote (the
  reference's remoteDCIsHealthy / datacenterVersionDifference check,
  fdbserver/ClusterRecovery + Ratekeeper's GetHealthMetrics path).
* `RemoteDC.failover()` is the DR-promote path: recover the acked
  suffix from the primary's SATELLITE logs (if configured), stop
  routing, let remote storages drain, and return the takeover version.
  With satellites (cluster/logsystem.py: commits ack only after the
  stream is durable in the second in-region failure domain), a whole
  primary-DC death loses NOTHING — RPO=0, the reference's HA write
  path (ha-write-path.rst). Without satellites, a primary death serves
  the router watermark — a consistent prefix.
"""

from __future__ import annotations

from typing import Optional

from foundationdb_tpu.cluster.logsystem import LogSystem
from foundationdb_tpu.cluster.storage import StorageServer
from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG, TLogCommitRequest
from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.probes import declare, code_probe

declare("multiregion.failover", "multiregion.router_caught_up",
        "multiregion.satellite_recovery")


class LogRouter:
    """Pulls the primary's full mutation stream into the remote logs.

    LogRouter.actor.cpp's role: a pull cursor on the primary log system
    (peek LOG_STREAM_TAG), a version-chained push into the remote log
    system, and pop acknowledgment so the primary can trim.
    """

    def __init__(
        self,
        sched: Scheduler,
        primary: LogSystem,
        remote: LogSystem,
        *,
        name: str = "log-router",
        key_tags,  # callable key -> remote storage tag
        n_remote_tags: int = 1,
        poll_interval: float = 0.02,
    ):
        self.sched = sched
        self.primary = primary
        self.remote = remote
        self.name = name
        self.key_tags = key_tags
        self.n_remote_tags = n_remote_tags
        self.poll_interval = poll_interval
        self.pulled_version = remote.version.get()
        self._task = None

    def start(self) -> None:
        self.primary.register_consumer(self.name)
        self._task = self.sched.spawn(self._pull(), name=self.name)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        try:
            self.primary.unregister_consumer(self.name)
        except Exception as e:
            # primary may be dead at failover time — expected then, but
            # worth a trace line anywhere else
            from foundationdb_tpu.utils.trace import SEV_WARN, TraceEvent

            TraceEvent("RemoteUnregisterFailed", severity=SEV_WARN) \
                .detail("Router", self.name).detail("Err", repr(e)).log()

    async def _pull(self) -> None:
        while True:
            try:
                entries, _v = await self.primary.peek(
                    LOG_STREAM_TAG, self.pulled_version
                )
                for v, msgs in entries:
                    if v <= self.pulled_version:
                        continue
                    await self._push_remote(v, msgs)
                    self.pulled_version = v
                    self.primary.pop(
                        LOG_STREAM_TAG, v, consumer=self.name
                    )
                if not entries:
                    await self.sched.delay(self.poll_interval)
            except ActorCancelled:
                raise
            except Exception:
                # primary unreachable/dead (possibly discovered mid-pop):
                # keep what we have and keep polling — the failover path
                # takes it from here. The router must never die silently.
                await self.sched.delay(self.poll_interval)

    async def _push_remote(self, version: int, msgs) -> None:
        """Re-tag the full stream for the remote region's storages and
        push as an ordinary version-chained remote commit."""
        tagged: dict = {t: [] for t in range(self.n_remote_tags)}
        for m in msgs:
            for t in self._tags_of(m):
                tagged[t].append(m)
        await self.remote.commit(TLogCommitRequest(
            prev_version=self.remote.version.get(),
            version=version,
            messages=tagged,
            epoch=self.remote.epoch,
        ))

    def _tags_of(self, m) -> set:
        # sim mutations: ("set", key, value) / ("clear", begin, end) / ...
        if m[0] == "clear":
            # a range clear may span any number of remote shards:
            # broadcast (the reference computes exact intersecting tags;
            # broadcast is conservative and correct)
            return set(range(self.n_remote_tags))
        return {self.key_tags(m[1])}


class RemoteDC:
    """The remote region: its own log system + async storage replicas."""

    def __init__(
        self,
        sched: Scheduler,
        primary: LogSystem,
        *,
        n_tlogs: int = 1,
        n_storage: int = 1,
        storage_boundaries: Optional[list] = None,
        window_versions: int = 5_000_000,
    ):
        self.sched = sched
        self.primary = primary
        base = primary.version.get()
        self.logs = LogSystem(sched, n_tlogs, recovery_version=base)
        self.boundaries = storage_boundaries or []
        if len(self.boundaries) != n_storage - 1:
            raise ValueError(
                f"{len(self.boundaries)} boundaries for {n_storage} remote "
                f"storages: need n_storage-1 (a key mapping past the tag "
                f"table would kill the router)"
            )

        def key_tag(key: bytes) -> int:
            t = 0
            for b in self.boundaries:
                if key >= b:
                    t += 1
            return t

        self.storages = [
            StorageServer(
                sched, self.logs, tag=t, recovery_version=base,
                window_versions=window_versions,
            )
            for t in range(n_storage)
        ]
        self.router = LogRouter(
            sched, primary, self.logs,
            key_tags=key_tag, n_remote_tags=n_storage,
        )
        self._failed_over = False

    def start(self) -> None:
        self.router.start()
        for s in self.storages:
            s.start()

    def stop(self) -> None:
        self.router.stop()
        for s in self.storages:
            s.stop()

    def lag(self) -> int:
        """Primary->remote version distance (datacenterVersionDifference)."""
        return max(0, self.primary.version.get() - self.logs.version.get())

    async def wait_caught_up(self, *, to_version: int = None) -> None:
        """Block until the router has pulled (and remote logs hold)
        everything the primary acked up to `to_version` (default: the
        primary's current version)."""
        target = (
            self.primary.version.get() if to_version is None else to_version
        )
        await self.logs.version.when_at_least(target)
        code_probe(True, "multiregion.router_caught_up")

    async def failover(self) -> int:
        """Promote the remote region: recover any acked suffix from the
        primary's SATELLITE logs, stop routing, drain storages to the
        remote log version, lock the remote logs for a new epoch.

        Returns the takeover version. With satellites configured
        (ClusterConfig.n_satellite_logs > 0) this is RPO=0 even after a
        whole-primary-DC death: commits acked only after satellite
        durability, and the satellite stream replays here
        (TagPartitionedLogSystem + ha-write-path.rst). Without
        satellites, a primary death serves the router watermark — a
        consistent prefix (async-replication RPO > 0)."""
        code_probe(True, "multiregion.failover")
        # BEFORE stopping the router: stopping unregisters its consumer
        # from the primary system (satellites included), which releases
        # the retained stream we are about to replay.
        sat = next(
            (
                t
                for t, alive in zip(
                    self.primary.satellites, self.primary.satellite_live
                )
                if alive
            ),
            None,
        )
        if sat is not None:
            wm = self.logs.version.get()
            if sat.version.get() > wm:
                # the satellite holds acked versions the router never
                # pulled before the primary died: replay them through
                # the same re-tagging push (duplicates the router also
                # managed to push are version-deduped by the remote log)
                entries, _v = await sat.peek(LOG_STREAM_TAG, wm)
                for v, msgs in entries:
                    if v > self.logs.version.get():
                        await self.router._push_remote(v, msgs)
                code_probe(True, "multiregion.satellite_recovery")
        self.router.stop()
        takeover = self.logs.version.get()
        # drain: every remote storage applies through the takeover version
        for s in self.storages:
            await s.version.when_at_least(takeover)
        self.logs.lock(self.logs.epoch + 1)
        self._failed_over = True
        return takeover

    async def read_at(self, key: bytes, version: int):
        """Read from the remote replicas (post-failover serving path)."""
        tag = self.router.key_tags(key)
        return await self.storages[tag].get_value(key, version)
