"""CommitProxy: the 5-phase commit pipeline.

Behavioral mirror of `fdbserver/CommitProxyServer.actor.cpp`:

* `commit_batcher` (:361): accumulates client CommitTransactionRequests
  into batches bounded by count/bytes/interval.
* `commit_batch` (:2516-2555) phases:
  1. pre-resolution (:812): batches are version-ordered; get the
     (prev_version, version] pair from the Sequencer.
  2. resolution (:959): ResolutionRequestBuilder splits every txn's
     conflict ranges across resolvers by the key_resolvers partition
     (:105-261) — each resolver sees only the pieces in its partition but
     every resolver sees every batch version (the version chain); state
     transactions go to all resolvers.
  3. post-resolution (:2045): committed = min over the verdicts of the
     resolvers each txn touched (determineCommittedTransactions
     :1551-1567); metadata mutations of committed state txns apply to the
     txn-state store (applyMetadataToCommittedTransactions :1596);
     mutations get storage tags by key_servers shard
     (assignMutationsToStorageServers :1861).
  4. transaction logging (:2294): one TLog push per batch, version chained.
  5. reply (:2333): report the live committed version to the Sequencer,
     then answer clients (committed version / not_committed with the
     conflicting-range report).

Batch pipelining: successive batches overlap; ordering is enforced by the
latest_batch_resolving / latest_batch_logging Notified chains
(:822-853, 1020), exactly the reference's NotifiedVersion discipline.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.runtime.flow import (
    Notified,
    Promise,
    PromiseStream,
    Scheduler,
    all_of,
)
from foundationdb_tpu.utils import commit_debug as _cd
from foundationdb_tpu.utils import trace as _trace
from foundationdb_tpu.utils.metrics import (
    COMMIT_LATENCY_BANDS,
    CounterCollection,
    LatencyBands,
    LatencySample,
)
from foundationdb_tpu.utils.probes import code_probe, declare

declare("proxy.conservative_write_injected", "proxy.min_combine_abort")

from foundationdb_tpu.models.types import (  # noqa: F401 (re-export)
    SYSTEM_PREFIX,
    is_metadata_mutation as _is_metadata_shared,
)


#: the databaseLocked key (cluster/dr.py writes it; the reference's
#: analog is \xff/dbLocked consulted by proxies via the txnStateStore)
DB_LOCK_KEY = b"\xff/dr/locked"


class DatabaseLockedError(Exception):
    """error_code_database_locked: commits refused while the database is
    locked (DR destination / retired DR source)."""


class NotCommitted(Exception):
    """error_code_not_committed; carries the conflicting read-range report."""

    def __init__(self, conflicting_ranges: Optional[list[int]] = None):
        super().__init__("transaction conflict")
        self.conflicting_ranges = conflicting_ranges


class TransactionTooOldError(Exception):
    """error_code_transaction_too_old from the resolver verdict."""


class CommitUnknownResult(Exception):
    """error_code_commit_unknown_result: the proxy died mid-commit; the
    transaction may or may not have committed (retryable, as in the
    reference's client onError)."""


@dataclasses.dataclass
class CommitID:
    """Commit reply payload (the reference's CommitID): the version plus
    the 10-byte versionstamp (8B big-endian version + 2B batch order)."""

    version: int
    versionstamp: bytes


@dataclasses.dataclass
class CommitRequest:
    transaction: CommitTransaction
    reply: Promise  # -> CommitID, or error
    # arrival time (virtual) — commit latency bands; None for synthetic
    # requests (conservative writes) that never came from a client
    start: Optional[float] = None


@dataclasses.dataclass
class KeyPartition:
    """Static key-range partition: boundaries[i] starts shard i+1.

    Stands in for the dynamic keyResolvers / keyServers maps
    (CommitProxyServer.actor.cpp:147-196, fdbclient/SystemData.cpp).
    """

    boundaries: list[bytes]

    @property
    def n_shards(self) -> int:
        return len(self.boundaries) + 1

    def shard_of(self, key: bytes) -> int:
        s = 0
        for b in self.boundaries:
            if key >= b:
                s += 1
            else:
                break
        return s

    def clip(self, begin: bytes, end: bytes, shard: int):
        lo = self.boundaries[shard - 1] if shard > 0 else b""
        hi = self.boundaries[shard] if shard < len(self.boundaries) else None
        cb = max(begin, lo)
        ce = end if hi is None else min(end, hi)
        return (cb, ce) if cb < ce else None

    def shards_of_range(self, begin: bytes, end: bytes) -> list[int]:
        return [
            s for s in range(self.n_shards)
            if self.clip(begin, end, s) is not None
        ]


class CommitProxy:
    def __init__(
        self,
        sched: Scheduler,
        proxy_id: str,
        sequencer,
        resolvers: list,            # objects with .resolve(req) coroutine
        tlog,                       # TLog
        key_resolvers: KeyPartition,
        key_servers: KeyPartition,
        *,
        epoch: int = 1,
        batch_interval: float = 0.005,
        max_batch_txns: int = 512,
        on_state_mutation: Optional[Callable[[Any], None]] = None,
        txn_state_view: Optional[dict] = None,
    ):
        self.sched = sched
        self.epoch = epoch
        self.proxy_id = proxy_id
        self.sequencer = sequencer
        self.resolvers = resolvers
        self.tlog = tlog
        self.key_resolvers = key_resolvers
        self.key_servers = key_servers
        self.batch_interval = batch_interval
        self.max_batch_txns = max_batch_txns
        # Adaptive batching (the reference's dynamic commitBatcher):
        # ctor args seed the controller — batch_interval is the initial
        # accumulation window, max_batch_txns the initial count target —
        # and the knob bounds cap every excursion. See cluster/batching.
        from foundationdb_tpu.cluster.batching import AdaptiveBatchSizer
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _K

        # max_interval is capped at the ctor interval: adaptivity only
        # SHRINKS the window under load and relaxes back to the
        # configured cadence — idle behavior is byte-identical to a
        # fixed-interval proxy (existing sims keep their schedules).
        self.batch_sizer = AdaptiveBatchSizer(
            interval=batch_interval,
            min_interval=min(
                batch_interval, _K.COMMIT_TRANSACTION_BATCH_INTERVAL_MIN
            ),
            max_interval=min(
                batch_interval,
                _K.COMMIT_TRANSACTION_BATCH_INTERVAL_MAX,
            ),
            target_count=max_batch_txns,
            max_count=max(
                max_batch_txns, _K.COMMIT_TRANSACTION_BATCH_COUNT_MAX
            ),
            max_bytes=_K.COMMIT_TRANSACTION_BATCH_BYTES_MAX,
            latency_budget=_K.COMMIT_BATCH_STAGE_LATENCY_BUDGET,
            alpha=_K.COMMIT_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA,
            latency_fraction=_K.COMMIT_TRANSACTION_BATCH_INTERVAL_LATENCY_FRACTION,
        )
        self.on_state_mutation = on_state_mutation
        # read-only view of the materialized txn-state store: the
        # dbLocked check consults it so EVERY client handle is covered
        self.txn_state_view = txn_state_view if txn_state_view is not None else {}

        self.requests = PromiseStream()
        self._batch_num = 0
        self._request_num = 0
        self.latest_batch_resolving = Notified(0)
        self.latest_batch_logging = Notified(0)
        self.last_received_version = 0
        self.committed_version = Notified(0)
        self.counters = CounterCollection(
            "ProxyMetrics",
            ["txnCommitIn", "txnCommitOut", "txnConflicts", "commitBatchIn"],
        )
        # commit latency distribution + reference-style bands
        # (CommitProxyServer.actor.cpp commitLatencyBands): request
        # arrival -> reply, in virtual time
        self.commit_latency = LatencySample("commitLatency")
        self.latency_bands = LatencyBands(
            "CommitLatencyMetrics", COMMIT_LATENCY_BANDS
        )
        # busiest-write-tag sensor (ISSUE 20): committed mutation bytes
        # per tag prefix, virtual-clock smoothed (deterministic)
        from foundationdb_tpu.cluster.sampling import TagCounter

        self.write_tags = TagCounter(clock=sched.now)
        self.failed: Optional[BaseException] = None
        # Ranges recently moved between resolvers (ResolutionBalancer):
        # the next batch injects a synthetic blind write over each so the
        # receiving resolver's empty history can't miss stale-read
        # conflicts (the reference applies resolverChanges with the same
        # conservative effect at the transition version).
        self.conservative_writes: list[tuple[bytes, bytes]] = []
        self._task = None
        # INSERTION-ORDERED (dict-as-set, not set): stop() cancels these
        # tasks in iteration order, and a set of Task OBJECTS iterates
        # in id()-hash order — allocation addresses, which vary run to
        # run. A recovery killing a proxy with two in-flight batches
        # then cancels them in varying order, the clients' unknown-
        # result deliveries swap, and the simulation DIVERGES between
        # identical seeds (found by the r5 ensemble's determinism
        # re-runs at 3/2000 seeds; reproduced + bisected via scheduler
        # event-stream diffing).
        self._inflight: dict = {}
        self._collecting: list[CommitRequest] = []
        # BUGGIFY_DUPLICATE_RESOLVE: recent resolve requests kept for
        # replay (a proxy retry after a lost reply). Old entries replay
        # as requests the resolver has pruned from its reply window.
        self._replay_ring: list = []
        # armed stream waiter carried across idle batcher rounds
        self._pending_next = None

    def start(self) -> None:
        self._task = self.sched.spawn(self._batcher(), name=f"{self.proxy_id}-batcher")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        # In-flight batches may be wedged on a dead peer's version chain
        # (e.g. a partitioned resolver); cancel them — the error path
        # answers their clients with commit_unknown_result.
        for task in list(self._inflight):
            task.cancel()
        self._inflight.clear()
        # Queued, collected-but-undispatched, or in-stream requests would
        # otherwise dangle forever; the reference's clients see
        # broken_promise from a dead proxy.
        for req in self._collecting:
            if not req.reply.is_set:
                req.reply.send_error(CommitUnknownResult())
        self._collecting = []
        # a request captured by the armed idle waiter must not dangle
        if self._pending_next is not None:
            if self._pending_next.is_ready and not self._pending_next.is_error:
                req = self._pending_next.get()
                if not req.reply.is_set:
                    req.reply.send_error(CommitUnknownResult())
            self._pending_next = None
        queue = self.requests.stream._queue
        while queue:
            req = queue.pop(0)
            if not req.reply.is_set:
                req.reply.send_error(CommitUnknownResult())

    # -- saturation sensors ------------------------------------------------

    def saturation(self) -> dict:
        """The commit proxy's qos sensor block: in-flight batch depth
        (the pipelined-batch overlap the Notified chains order), queued
        and mid-accumulation requests, and the AdaptiveBatchSizer's live
        interval/count/bytes targets — the control surface the future
        Ratekeeper reads before deciding a txn/s budget."""
        return {
            "inflight_batches": len(self._inflight),
            "queued_requests": (
                len(self.requests.stream._queue) + len(self._collecting)
            ),
            "batches_started": self._batch_num,
            "batches_logged": self.latest_batch_logging.get(),
            "batch_sizer": self.batch_sizer.as_dict(),
            # r19 scale-out sensors, shared schema with the wire proxy:
            # grants = GetCommitVersion round-trips to the sequencer;
            # tag_partitioned reports the log front's REAL per-tag
            # fan-out state (LogSystem.tag_partitioned — the PR-19
            # remaining (b) fix), so the sensor means the same thing
            # the wire pipeline's does
            "version_grants": self._request_num,
            "tag_partitioned": bool(
                getattr(self.tlog, "tag_partitioned", False)
            ),
            "failed": self.failed is not None,
            # busiest-write-tag (ISSUE 20): committed bytes by tag
            # prefix as assigned to storage tags in _assign_mutations
            "busiest_write_tag": self.write_tags.busiest(),
        }

    # -- client entry -----------------------------------------------------

    def commit(self, txn: CommitTransaction) -> Promise:
        p = Promise()
        self.counters.add("txnCommitIn")
        if self.failed is not None or self._task is None:
            # Dead/stopped proxy: the retryable commit_unknown_result, as
            # the reference's clients see while recovery replaces the
            # generation (fdbserver/ClusterRecovery.actor.cpp).
            p.send_error(CommitUnknownResult())
            return p
        self.requests.send(CommitRequest(txn, p, start=self.sched.now()))
        return p

    # -- phase 0: batching (commitBatcher :361) ----------------------------

    async def _batcher(self) -> None:
        from foundationdb_tpu.cluster.batching import commit_txn_bytes
        from foundationdb_tpu.runtime.flow import any_of

        while True:
            # Wait for traffic, but never idle past the forced-batch
            # interval: an idle proxy still emits EMPTY batches so its
            # lastVersion keeps advancing at every resolver — otherwise
            # retained state transactions (consumed only once every proxy
            # has passed them) pin resolver memory and the backpressure
            # loop can wedge the whole pipeline on one quiet proxy
            # (the reference's commitBatcher forced-batch behavior,
            # CommitProxyServer.actor.cpp commitBatcher's
            # MAX_COMMIT_BATCH_INTERVAL).
            # The head request always comes through the tracked armed
            # waiter: send() delivers values INTO waiter futures, so a
            # stop() between delivery and resumption would orphan an
            # untracked one (stop recovers self._pending_next).
            sizer = self.batch_sizer
            ok, first = self.requests.stream.try_next()
            if not ok:
                if self._pending_next is None:
                    self._pending_next = self.requests.stream.next()
                idx, val = await any_of(
                    [
                        self._pending_next,
                        self.sched.delay(10 * sizer.interval),
                    ]
                )
                if idx == 1:
                    self._spawn_batch([])  # idle forced empty batch
                    continue
                self._pending_next = None
                first = val
            # self._collecting is visible to stop(): requests gathered but
            # not yet dispatched must not die silently with the batcher.
            batch = self._collecting = [first]
            # adaptive targets, snapshotted at batch open (the controller
            # moves between batches, never mid-accumulation)
            count_target = min(sizer.target_count, self.max_batch_txns)
            bytes_target = sizer.target_bytes
            batch_bytes = commit_txn_bytes(first.transaction)
            deadline = self.sched.now() + sizer.interval

            def drain():
                nonlocal batch_bytes
                while (
                    len(batch) < count_target
                    and batch_bytes < bytes_target
                ):
                    ok, req = self.requests.stream.try_next()
                    if not ok:
                        return
                    batch.append(req)
                    batch_bytes += commit_txn_bytes(req.transaction)

            def full() -> bool:
                return (
                    len(batch) >= count_target
                    or batch_bytes >= bytes_target
                )

            drain()
            # allow a short accumulation window
            while not full() and self.sched.now() < deadline:
                await self.sched.delay(sizer.interval / 4)
                drain()
            self._collecting = []
            # dispatch-side feedback: a full batch means traffic outran
            # the window (shrink it); an underfull interval-expiry batch
            # relaxes it back toward the MAX knob
            if full():
                sizer.batch_full()
            else:
                sizer.batch_underfull(len(batch))
            self._spawn_batch(batch, was_full=full())

    def _spawn_batch(self, batch: list, was_full: bool = False) -> None:
        self._batch_num += 1
        task = self.sched.spawn(
            self._commit_batch(batch, self._batch_num, was_full),
            name=f"{self.proxy_id}-batch{self._batch_num}",
        )
        self._inflight[task] = None
        task.done.add_done_callback(
            lambda _f, t=task: self._inflight.pop(t, None)
        )

    # -- phases 1-5 (commitBatch :2516) ------------------------------------

    async def _commit_batch(
        self, batch: list[CommitRequest], batch_num: int,
        was_full: bool = False,
    ) -> None:
        try:
            await self._commit_batch_impl(batch, batch_num, was_full)
        except BaseException as e:
            # An internal failure must not strand the clients (their reply
            # futures) nor leave the error invisible. The version chain may
            # now have a hole, so the proxy marks itself broken — the
            # reference's equivalent outcome is a recovery.
            self.failed = e
            for r in batch:
                if not r.reply.is_set:
                    r.reply.send_error(CommitUnknownResult())
            raise

    async def _commit_batch_impl(
        self, batch: list[CommitRequest], batch_num: int,
        was_full: bool = False,
    ) -> None:
        self.counters.add("commitBatchIn")
        # span per commit batch (the reference's commitBatch span,
        # Tracing.actor.cpp); children: the resolution requests. The
        # span parents on the first traced transaction's client span
        # (the reference's multi-parent span collapsed to one edge), so
        # a trace runs client -> proxy -> resolver.
        from foundationdb_tpu.utils.spans import Span, SpanContext

        parent = next(
            (
                SpanContext(*r.transaction.span)
                for r in batch
                if r.transaction.span is not None
            ),
            None,
        )
        batch_span = Span(
            f"{self.proxy_id}.commitBatch", parent=parent,
            clock=self.sched.now,
        ).attribute("txns", len(batch))
        # batch debug id (deterministic — the reference draws one at
        # random and attaches every member txn's id to it): emitted only
        # when some member is traced
        dbg = None
        if any(r.transaction.debug_id is not None for r in batch):
            dbg = f"{self.proxy_id}-b{batch_num}"
            for r in batch:
                if r.transaction.debug_id is not None:
                    _trace.g_trace_batch.add_attach(
                        "CommitAttachID", r.transaction.debug_id, dbg
                    )
            _trace.g_trace_batch.add_event(
                "CommitDebug", dbg, _cd.BATCH_BEFORE
            )
        try:
            await self._commit_batch_spanned(
                batch, batch_num, batch_span, dbg, was_full
            )
        finally:
            # failure paths (dead resolver, recovery kill) still export
            batch_span.finish()

    async def _commit_batch_spanned(
        self, batch, batch_num, batch_span, dbg, was_full=False
    ):
        # databaseLocked (NativeAPI's commit check against \xff/dbLocked,
        # here proxy-side via the materialized txn-state store so no
        # client handle can bypass it): non-lock-aware txns fail fast.
        if self.txn_state_view.get(DB_LOCK_KEY) is not None:
            passing = []
            for r in batch:
                if getattr(r.transaction, "lock_aware", False):
                    passing.append(r)
                else:
                    r.reply.send_error(DatabaseLockedError())
            batch = passing
            if not batch:
                # the batch-ordering chains must still advance — IN ORDER
                # (set() without awaiting the predecessor would violate
                # the monotonic Notified contract when an earlier batch
                # is still mid-flight)
                await self.latest_batch_resolving.when_at_least(batch_num - 1)
                self.latest_batch_resolving.set(batch_num)
                await self.latest_batch_logging.when_at_least(batch_num - 1)
                self.latest_batch_logging.set(batch_num)
                return
        txns = [r.transaction for r in batch]
        # Phase 1: order batches, get the version pair.
        await self.latest_batch_resolving.when_at_least(batch_num - 1)
        if dbg is not None:
            _trace.g_trace_batch.add_event(
                "CommitDebug", dbg, _cd.BATCH_GETTING_VERSION
            )
        self._request_num += 1
        vreply = await self.sequencer.get_commit_version(
            self.proxy_id, self._request_num, self._request_num
        )
        prev_version, version = vreply.prev_version, vreply.version
        if dbg is not None:
            _trace.g_trace_batch.add_event(
                "CommitDebug", dbg, _cd.BATCH_GOT_VERSION
            )

        # Phase 2: resolution.
        if self.conservative_writes:
            code_probe(True, "proxy.conservative_write_injected")
            moved, self.conservative_writes = self.conservative_writes, []
            # PREPENDED: intra-batch conflicts only see lower-indexed
            # writers, so the synthetic write must come before every user
            # transaction to abort same-batch stale reads of the moved
            # span (the reference applies resolverChanges before the
            # batch's transactions).
            batch = [
                CommitRequest(
                    CommitTransaction(write_conflict_ranges=list(moved)),
                    Promise(),
                )
            ] + batch
            txns = [r.transaction for r in batch]
        reqs, txn_resolver_map, range_maps = self._build_resolution_requests(
            txns, prev_version, version
        )
        for rq in reqs:
            rq.span = batch_span.context.as_tuple()
            rq.debug_id = dbg
        self.latest_batch_resolving.set(batch_num)
        _t_resolve = self.sched.now()
        replies = await all_of(
            [
                self.sched.spawn(res.resolve(req)).done
                for res, req in zip(self.resolvers, reqs)
            ]
        )
        _resolve_s = self.sched.now() - _t_resolve
        self.last_received_version = version
        if dbg is not None:
            _trace.g_trace_batch.add_event(
                "CommitDebug", dbg, _cd.BATCH_AFTER_RESOLUTION
            )
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        if SERVER_KNOBS.BUGGIFY_DUPLICATE_RESOLVE:
            # Re-send resolve requests the resolver has already answered —
            # the retry-after-lost-reply path (Resolver.actor.cpp:513
            # returns the cached reply; requests pruned from the reply
            # window return Never(), so replays are fire-and-forget).
            async def _replay(res, req):
                try:
                    await res.resolve(req)
                # a replayed duplicate is BUGGIFY noise by contract: the
                # real request's error path already ran
                except Exception:  # flowcheck: ignore[actor.swallow]
                    pass

            self._replay_ring.append((self.resolvers[0], reqs[0]))
            if version % 2 == 0:
                # fire-and-forget by design: _replay contains its errors
                self.sched.spawn(_replay(self.resolvers[0], reqs[0]))  # flowcheck: ignore[actor.fire-and-forget]
            if len(self._replay_ring) > 6 and version % 3 == 0:
                res_old, req_old = self._replay_ring.pop(0)
                self.sched.spawn(_replay(res_old, req_old))  # flowcheck: ignore[actor.fire-and-forget]
            del self._replay_ring[:-8]

        # Phase 3: post-resolution (order by logging chain).
        await self.latest_batch_logging.when_at_least(batch_num - 1)
        verdicts, conflict_reports = self._determine_committed(
            txns, replies, txn_resolver_map, range_maps
        )

        # State mutations from other proxies' prior versions first, then
        # this batch's own committed metadata mutations. With the
        # PROXY_USE_RESOLVER_PRIVATE_MUTATIONS knob on, the batch's own
        # metadata arrives resolver-generated (reply.private_mutations,
        # Resolver.actor.cpp:372-441) instead of being re-derived here —
        # the resolver's materialized txnStateStore is authoritative.
        if self.on_state_mutation is not None:
            for group in replies[0].state_mutations:
                for st in group:
                    if st.committed:
                        for m in st.mutations:
                            # a state txn may mix user mutations in; only
                            # metadata belongs in the txn-state store
                            if _is_metadata(m):
                                self.on_state_mutation(m)
            if replies[0].private_mutations:
                # resolver-generated candidates, filtered by the GLOBAL
                # verdict (a locally-committed state txn may be aborted
                # by another resolver's shard)
                for t, tr in enumerate(txns):
                    if verdicts[t] != TransactionResult.COMMITTED:
                        continue
                    local = txn_resolver_map[t].get(0)
                    if local is None:
                        continue
                    for m in replies[0].private_mutations.get(local, []):
                        self.on_state_mutation(m)
            else:
                for t, tr in enumerate(txns):
                    if verdicts[t] == TransactionResult.COMMITTED:
                        for m in tr.mutations:
                            if _is_metadata(m):
                                self.on_state_mutation(m)

        messages = self._assign_mutations(txns, verdicts, version)

        # Phase 4: push to the log system.
        from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG, TLogCommitRequest

        if dbg is not None:
            # the batch-id -> commit-version join record: storage applies
            # are keyed by version, this is how commit_debug ties them in
            _trace.TraceEvent(
                "CommitDebugVersion", severity=_trace.SEV_DEBUG
            ).detail("ID", dbg).detail("Version", version).detail(
                "Messages",
                sum(1 for tag in messages if tag != LOG_STREAM_TAG),
            ).log()
        _t_log = self.sched.now()
        await self.tlog.commit(
            TLogCommitRequest(
                prev_version=prev_version, version=version, messages=messages,
                epoch=self.epoch, debug_id=dbg,
                span=batch_span.context.as_tuple(),
            )
        )
        self.latest_batch_logging.set(batch_num)
        if batch:
            # completion-side feedback: count/bytes targets follow the
            # measured resolve+log stage seconds (empty idle batches
            # carry no sizing evidence and are excluded)
            self.batch_sizer.observe_stage_latency(
                _resolve_s + (self.sched.now() - _t_log), full=was_full
            )
        if dbg is not None:
            _trace.g_trace_batch.add_event(
                "CommitDebug", dbg, _cd.BATCH_AFTER_LOG_PUSH
            )

        # Phase 5: reply.
        batch_span.attribute("version", version)
        self.sequencer.report_live_committed_version(version)
        self.committed_version.set(version)
        now = self.sched.now()
        for t, req in enumerate(batch):
            v = verdicts[t]
            if req.start is not None:
                dt = now - req.start
                self.commit_latency.sample(dt)
                self.latency_bands.add(dt)
            if v == TransactionResult.COMMITTED:
                self.counters.add("txnCommitOut")
                req.reply.send(CommitID(version, _stamp(version, t)))
            elif v == TransactionResult.TOO_OLD:
                req.reply.send_error(TransactionTooOldError())
            else:
                self.counters.add("txnConflicts")
                req.reply.send_error(NotCommitted(conflict_reports.get(t)))

    # -- ResolutionRequestBuilder (:105-261) --------------------------------

    def _build_resolution_requests(self, txns, prev_version, version):
        n_res = len(self.resolvers)
        per_res_txns: list[list[CommitTransaction]] = [[] for _ in range(n_res)]
        per_res_state: list[list[int]] = [[] for _ in range(n_res)]
        txn_resolver_map: list[dict[int, int]] = []  # t -> {resolver: local idx}
        range_maps: list[dict[int, list[int]]] = []  # t -> {res: local->orig read idx}

        for t, tr in enumerate(txns):
            is_state = any(_is_metadata(m) for m in tr.mutations)
            targets: dict[int, CommitTransaction] = {}
            ridx: dict[int, list[int]] = {}
            for i, (b, e) in enumerate(tr.read_conflict_ranges):
                for s in self.key_resolvers.shards_of_range(b, e):
                    lt = targets.setdefault(
                        s,
                        CommitTransaction(
                            read_snapshot=tr.read_snapshot,
                            report_conflicting_keys=tr.report_conflicting_keys,
                        ),
                    )
                    lt.read_conflict_ranges.append(self.key_resolvers.clip(b, e, s))
                    ridx.setdefault(s, []).append(i)
            for b, e in tr.write_conflict_ranges:
                for s in self.key_resolvers.shards_of_range(b, e):
                    lt = targets.setdefault(
                        s,
                        CommitTransaction(
                            read_snapshot=tr.read_snapshot,
                            report_conflicting_keys=tr.report_conflicting_keys,
                        ),
                    )
                    lt.write_conflict_ranges.append(self.key_resolvers.clip(b, e, s))
            if is_state:
                # state txns go to every resolver (with their mutations)
                for s in range(n_res):
                    lt = targets.setdefault(
                        s,
                        CommitTransaction(
                            read_snapshot=tr.read_snapshot,
                            report_conflicting_keys=tr.report_conflicting_keys,
                        ),
                    )
                    lt.mutations = list(tr.mutations)
            tmap: dict[int, int] = {}
            for s, lt in targets.items():
                tmap[s] = len(per_res_txns[s])
                per_res_txns[s].append(lt)
                if is_state:
                    per_res_state[s].append(tmap[s])
            txn_resolver_map.append(tmap)
            range_maps.append(ridx)

        # version-vector path (knob-gated): ship the batch's written
        # storage tags so resolvers can answer tpcvMap
        # (ResolverInterface.h:139 writtenTags)
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        written_tags: frozenset = frozenset()
        if SERVER_KNOBS.ENABLE_VERSION_VECTOR_TLOG_UNICAST:
            tags: set = set()
            for tr in txns:
                for b, e in tr.write_conflict_ranges:
                    tags.update(self.key_servers.tags_of_range(b, e))
            written_tags = frozenset(tags)

        reqs = [
            ResolveTransactionBatchRequest(
                prev_version=prev_version,
                version=version,
                last_received_version=self.last_received_version,
                transactions=per_res_txns[s],
                txn_state_transactions=per_res_state[s],
                proxy_id=self.proxy_id,
                written_tags=written_tags,
            )
            for s in range(n_res)
        ]
        return reqs, txn_resolver_map, range_maps

    # -- determineCommittedTransactions (:1551-1567) -------------------------

    def _determine_committed(self, txns, replies, txn_resolver_map, range_maps):
        verdicts: list[TransactionResult] = []
        reports: dict[int, list[int]] = {}
        for t in range(len(txns)):
            v = TransactionResult.COMMITTED
            locals_seen = []
            for s, local in txn_resolver_map[t].items():
                locals_seen.append(int(replies[s].committed[local]))
                v = min(v, replies[s].committed[local])
            # a txn one resolver would commit but another aborts: the
            # min-combine doing real cross-shard work
            code_probe(
                len(locals_seen) > 1
                and v != TransactionResult.COMMITTED
                and any(x == TransactionResult.COMMITTED for x in locals_seen),
                "proxy.min_combine_abort",
            )
            verdicts.append(TransactionResult(v))
            if v == TransactionResult.CONFLICT and txns[t].report_conflicting_keys:
                idxs: set[int] = set()
                for s, local in txn_resolver_map[t].items():
                    lmap = range_maps[t].get(s)  # local read idx -> original
                    for li in replies[s].conflicting_key_range_map.get(local, []):
                        idxs.add(lmap[li] if lmap is not None else li)
                reports[t] = sorted(idxs)
        return verdicts, reports

    # -- assignMutationsToStorageServers (:1861) ------------------------------

    def _assign_mutations(self, txns, verdicts, version: int) -> dict[int, list[Any]]:
        messages: dict[int, list[Any]] = {}
        # full-stream tag for log-consuming workers (backup/DR): each
        # committed mutation EXACTLY ONCE, in commit order — per-storage
        # tags duplicate a mutation per team replica, which would
        # double-apply atomics on replay (BackupWorker's dedicated tags
        # exist for the same reason)
        from foundationdb_tpu.cluster.sampling import tag_of_key
        from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG

        emit_stream = self.tlog.has_log_consumers()
        for t, tr in enumerate(txns):
            if verdicts[t] != TransactionResult.COMMITTED:
                continue
            for m in tr.mutations:
                kind = m[0]
                if kind == "vs_key":
                    # SetVersionstampedKey: splice the commit stamp into
                    # the key, then it is an ordinary set.
                    _, prefix, suffix, value = m
                    m = ("set", prefix + _stamp(version, t) + suffix, value)
                    kind = "set"
                elif kind == "vs_value":
                    _, key, value_prefix = m
                    m = ("set", key, value_prefix + _stamp(version, t))
                    kind = "set"
                if kind == "set":
                    span = (m[1], m[1] + b"\x00")
                    shards = list(self.key_servers.team_of(m[1]))
                elif kind == "atomic":
                    span = (m[2], m[2] + b"\x00")
                    shards = list(self.key_servers.team_of(m[2]))
                elif kind == "clear":
                    span = (m[1], m[2])
                    shards = self.key_servers.tags_of_range(m[1], m[2])
                else:
                    raise ValueError(f"unknown mutation {m!r}")
                # dual-tag state lives on the SHARED shard map so it
                # survives proxy-generation changes (see ShardMap)
                for b, e, tag in self.key_servers.extra_tag_ranges:
                    if span[0] < e and b < span[1] and tag not in shards:
                        shards.append(tag)
                for s in shards:
                    messages.setdefault(s, []).append(m)
                if emit_stream:
                    messages.setdefault(LOG_STREAM_TAG, []).append(m)
                # busiest-write-tag sensor (ISSUE 20): committed bytes
                # by tag prefix, counted once per mutation (not per
                # replica — the client wrote it once)
                try:
                    nb = 8 + len(m[1]) + len(m[2])
                except Exception:
                    nb = 32
                self.write_tags.note(tag_of_key(span[0]), nb)
        return messages


def _stamp(version: int, order: int) -> bytes:
    """10-byte versionstamp: 8B big-endian commit version + 2B txn order."""
    return version.to_bytes(8, "big") + order.to_bytes(2, "big")


def _is_metadata(m) -> bool:
    """Metadata mutations target the \xff system keyspace
    (the applyMetadataToCommittedTransactions condition)."""
    return _is_metadata_shared(m)
