"""ResolutionBalancer: dynamic key-range rebalancing across resolvers.

Behavioral mirror of `fdbserver/ResolutionBalancer.actor.cpp:30-188`:
the sequencer-side control loop polls each resolver's sampled load
(ResolutionMetricsRequest — our Resolver.metrics()), and when the
busiest resolver carries more than its fair share it asks it for a
split key (ResolutionSplitRequest — Resolver.split_point()) and moves
the boundary toward the less-loaded neighbor. Changes apply atomically
to the shared KeyPartition that proxies consult when splitting conflict
ranges (the reference piggybacks resolverChanges on
GetCommitVersionReply; here proxies read the live partition object).
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection

MIN_BALANCE_TIME = 0.2
IMBALANCE_RATIO = 1.5  # rebalance when max load > ratio * average


class ResolutionBalancer:
    def __init__(
        self,
        sched: Scheduler,
        resolvers: list,
        key_resolvers,   # cluster's KeyPartition (mutated in place)
        commit_proxies: list = (),
        *,
        interval: float = 0.5,
    ):
        self.sched = sched
        self.resolvers = resolvers
        self.key_resolvers = key_resolvers
        self.commit_proxies = list(commit_proxies)
        self.interval = interval
        self.counters = CounterCollection("BalancerMetrics", ["loops", "moves"])
        self._last_move = -float("inf")
        self._task = None

    def start(self) -> None:
        if len(self.resolvers) > 1:
            self._task = self.sched.spawn(self._loop(), name="resolution-balancer")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def rebalance_once(self) -> bool:
        """One balancing decision (resolutionBalancing_impl :115): shed
        load from the busiest resolver to its LESS-loaded adjacent
        neighbor, rate-limited by MIN_BALANCE_TIME."""
        now = self.sched.now()
        if now - self._last_move < MIN_BALANCE_TIME:
            return False
        loads = [r.metrics() for r in self.resolvers]
        total = sum(loads)
        if total == 0:
            return False
        avg = total / len(loads)
        busiest = max(range(len(loads)), key=lambda i: loads[i])
        if loads[busiest] <= IMBALANCE_RATIO * avg:
            return False
        b = self.key_resolvers.boundaries
        lo = b[busiest - 1] if busiest > 0 else b""
        hi = b[busiest] if busiest < len(b) else b"\xff" * 64
        # candidate recipients: adjacent shards, lightest (and lighter than
        # average) first — never push load onto another hot shard
        neighbors = [
            i for i in (busiest - 1, busiest + 1)
            if 0 <= i < len(loads) and loads[i] < avg
        ]
        for nb in sorted(neighbors, key=lambda i: loads[i]):
            split = self.resolvers[busiest].split_point(lo, hi, 0.5)
            if not (lo < split < hi):
                continue
            if nb == busiest + 1:
                b[busiest] = split          # give the upper part rightward
                self._moved(split, hi)
            else:
                b[busiest - 1] = split      # give the lower part leftward
                self._moved(lo, split)
            self._last_move = now
            return True
        return False

    def _moved(self, begin: bytes, end: bytes) -> None:
        """Queue the conservative write over the moved span on every proxy
        (the receiving resolver has no history for it yet)."""
        self.counters.add("moves")
        for p in self.commit_proxies:
            p.conservative_writes.append((begin, end))

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.interval)
                self.counters.add("loops")
                self.rebalance_once()
        except ActorCancelled:
            raise
