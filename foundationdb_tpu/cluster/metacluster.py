"""Metacluster: tenant management across multiple data clusters.

Capability match for fdbclient/Metacluster*.cpp +
MetaclusterManagement.actor.h: one MANAGEMENT cluster stores the
registry of data clusters (capacity, connection info) and the
tenant->cluster assignment; tenant creation picks a data cluster with
free capacity, creates the tenant THERE, and records the assignment in
the management cluster; clients open a tenant by name through the
metacluster and get a handle bound to the right data cluster.

Concurrency/atomicity discipline (the reference's multi-step tenant
states, MetaclusterManagement CreateTenantImpl):

* Load accounting has ONE source of truth — the assignment rows
  themselves, counted inside the SAME transaction that writes a new
  assignment (read conflicts make concurrent creates serialize; no
  counter rows to drift).
* Cross-cluster steps are staged: the assignment is committed in state
  CREATING first, then the tenant is created on the data cluster
  (idempotently), then the assignment flips to READY — a crash between
  steps leaves a CREATING row that the next create/open repairs or
  surfaces, never an orphaned unreachable tenant.
* register_cluster writes the data cluster's registration marker FIRST
  (the double-registration guard must exist before the registry entry
  does); a partial failure is repaired by re-registering under the
  SAME name.
"""

from __future__ import annotations

import json

from foundationdb_tpu.cluster import tenant as T

_CLUSTERS = b"\xff/metacluster/clusters/"
_TENANTS = b"\xff/metacluster/tenants/"
_REGISTRATION = b"\xff/metacluster/registration"

_CREATING = b"\x00creating/"  # assignment-value prefix while staging


class ClusterExists(Exception):
    pass


class ClusterNotFound(Exception):
    pass


class ClusterNotEmpty(Exception):
    pass


class ClusterAlreadyRegistered(Exception):
    pass


class MetaclusterCapacityExceeded(Exception):
    pass


class Metacluster:
    """The management-cluster API. `data_dbs` maps cluster name ->
    Database handle (the reference stores ClusterConnectionString; in
    one process the handle IS the connection)."""

    def __init__(self, management_db):
        self.db = management_db
        self.data_dbs: dict[bytes, object] = {}

    # -- data-cluster registry (MetaclusterManagement register/remove) --

    async def register_cluster(self, name: bytes, data_db,
                               *, capacity: int = 10) -> None:
        # precheck the registry so a NAME COLLISION never writes the
        # marker (a poisoned marker would block the data cluster under
        # every name — third review pass); the marker then lands before
        # the registry entry (crash between the two re-registers under
        # the SAME name and repairs), and a post-commit ClusterExists
        # rolls the marker back.
        rtxn = data_db.create_transaction()
        existing = await rtxn.get(_REGISTRATION)
        if existing is not None and json.loads(existing)["name"] != (
            name.decode()
        ):
            raise ClusterAlreadyRegistered(
                f"data cluster already registered as "
                f"{json.loads(existing)['name']!r}"
            )
        pre = self.db.create_transaction()
        if await pre.get(_CLUSTERS + name) is not None:
            raise ClusterExists(name)
        if existing is None:
            rtxn.set(
                _REGISTRATION, json.dumps({"name": name.decode()}).encode()
            )
            await rtxn.commit()
        try:
            async def write_registry(txn):
                if await txn.get(_CLUSTERS + name) is not None:
                    raise ClusterExists(name)
                txn.set(
                    _CLUSTERS + name,
                    json.dumps({"capacity": capacity}).encode(),
                )

            # idempotent: a CommitUnknownResult whose commit APPLIED
            # must not re-read its own write and self-ClusterExists
            # (which would roll back a marker that should stand)
            await self.db.run(write_registry, idempotent=True)
        except ClusterExists:
            if existing is None:  # roll the fresh marker back
                rb = data_db.create_transaction()
                rb.clear(_REGISTRATION)
                await rb.commit()
            raise
        self.data_dbs[name] = data_db

    async def remove_cluster(self, name: bytes) -> None:
        async def remove(txn):
            meta = await txn.get(_CLUSTERS + name)
            if meta is None:
                raise ClusterNotFound(name)
            # assignment rows are the truth; the reads add conflict
            # ranges so a racing create_tenant serializes against the
            # removal
            assigned = await txn.get_range(_TENANTS, _TENANTS + b"\xff")
            hosted = [
                k for k, v in assigned
                if v == name or v == _CREATING + name
            ]
            if hosted:
                raise ClusterNotEmpty(
                    f"{name!r} still hosts {len(hosted)} tenants"
                )
            txn.clear(_CLUSTERS + name)

        # idempotent: an applied-but-unknown clear must not retry into
        # a spurious ClusterNotFound that skips the marker cleanup below
        await self.db.run(remove, idempotent=True)
        data_db = self.data_dbs.pop(name, None)
        if data_db is not None:
            rtxn = data_db.create_transaction()
            rtxn.clear(_REGISTRATION)
            await rtxn.commit()

    async def list_clusters(self) -> dict[bytes, dict]:
        txn = self.db.create_transaction()
        rows = await txn.get_range(_CLUSTERS, _CLUSTERS + b"\xff")
        assigned = await txn.get_range(_TENANTS, _TENANTS + b"\xff")
        out = {}
        for k, v in rows:
            cname = k[len(_CLUSTERS):]
            meta = json.loads(v)
            meta["tenants"] = sum(
                1 for _t, c in assigned
                if c == cname or c == _CREATING + cname
            )
            out[cname] = meta
        return out

    # -- tenant management (createTenant through the metacluster) --------

    async def create_tenant(self, name: bytes) -> bytes:
        """Assign the tenant to the least-loaded data cluster with free
        capacity, create it there, record the assignment. Staged:
        CREATING assignment -> data-cluster create -> READY."""
        # phase 1: commit the CREATING assignment. Reads of the
        # registry + every assignment ride THE COMMITTING transaction,
        # so two concurrent creates (or a racing remove_cluster)
        # conflict and serialize; Database.run supplies the standard
        # retry loop (the reference's management ops run under
        # runTransaction too — third review pass: no hand-rolled
        # weaker retry).
        async def phase1(txn):
            cur = await txn.get(_TENANTS + name)
            if cur is not None and not cur.startswith(_CREATING):
                raise T.TenantExists(name)
            if cur is not None:
                return cur[len(_CREATING):]  # crashed mid-create: repair
            clusters = await txn.get_range(_CLUSTERS, _CLUSTERS + b"\xff")
            assigned = await txn.get_range(_TENANTS, _TENANTS + b"\xff")
            load: dict[bytes, int] = {}
            for _t, c in assigned:
                c = c[len(_CREATING):] if c.startswith(_CREATING) else c
                load[c] = load.get(c, 0) + 1
            candidates = sorted(
                (load.get(k[len(_CLUSTERS):], 0), k[len(_CLUSTERS):])
                for k, v in clusters
                if load.get(k[len(_CLUSTERS):], 0) < json.loads(v)["capacity"]
            )
            if not candidates:
                raise MetaclusterCapacityExceeded(
                    "no data cluster has free tenant capacity"
                )
            chosen = candidates[0][1]
            txn.set(_TENANTS + name, _CREATING + chosen)
            return chosen

        chosen = await self.db.run(phase1)
        # phase 2: create on the data cluster — idempotent: a repair
        # pass finding it already there proceeds to phase 3
        try:
            await T.create_tenant(self.data_dbs[chosen], name)
        except T.TenantExists:
            pass
        # phase 3: flip to READY
        async def phase3(txn):
            txn.set(_TENANTS + name, chosen)

        await self.db.run(phase3)
        return chosen

    async def delete_tenant(self, name: bytes) -> None:
        txn = self.db.create_transaction()
        cname = await txn.get(_TENANTS + name)
        if cname is None:
            raise T.TenantNotFound(name)
        if cname.startswith(_CREATING):
            cname = cname[len(_CREATING):]
        # data-cluster delete FIRST (raises TenantNotEmpty with the
        # assignment intact); tolerate a repair pass where the tenant
        # never finished creating
        try:
            await T.delete_tenant(self.data_dbs[cname], name)
        except T.TenantNotFound:
            pass

        async def clear_assignment(txn):
            # re-read under THIS transaction: the read conflict makes a
            # concurrent delete+re-create abort us instead of the blind
            # clear silently erasing the NEW assignment
            cur = await txn.get(_TENANTS + name)
            if cur == cname or cur == _CREATING + cname:
                txn.clear(_TENANTS + name)

        await self.db.run(clear_assignment)

    async def list_tenants(self) -> dict[bytes, bytes]:
        txn = self.db.create_transaction()
        rows = await txn.get_range(_TENANTS, _TENANTS + b"\xff")
        return {
            k[len(_TENANTS):]: (
                v[len(_CREATING):] if v.startswith(_CREATING) else v
            )
            for k, v in rows
        }

    async def open_tenant(self, name: bytes) -> T.Tenant:
        """A tenant handle bound to its assigned data cluster. A
        CREATING assignment (crash mid-create) is repaired first."""
        txn = self.db.create_transaction()
        cname = await txn.get(_TENANTS + name)
        if cname is None:
            raise T.TenantNotFound(name)
        if cname.startswith(_CREATING):
            try:
                await self.create_tenant(name)  # finish the staged create
            except T.TenantExists:
                pass  # a concurrent repair won the race — equally done
            cname = cname[len(_CREATING):]
        return T.Tenant(self.data_dbs[cname], name)
