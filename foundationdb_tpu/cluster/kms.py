"""KMS connectors: where encryption base secrets come from.

The reference speaks to a KMS through a connector interface —
fdbserver/KmsConnectorInterface.h — with two implementations:
SimKmsConnector.actor.cpp (deterministic in-memory keys for simulation)
and RESTKmsConnector.actor.cpp (a REST KMS over HTTP). Both shapes are
here: SimKmsConnector derives deterministic per-domain base secrets from
a master seed, and RestKmsConnector speaks JSON-over-HTTP to any server
implementing the two-endpoint surface (a stub server for tests is in
`serve_stub_kms`, standing in for the external KMS the reference
assumes).

A base secret never leaves the KMS boundary unwrapped in the reference's
production deployment; here the connector returns it to the
EncryptKeyProxy, which derives record keys and hands only DERIVED keys
to roles (crypto/blob_cipher.derive_key) — the same trust split.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import threading


class KmsError(RuntimeError):
    pass


class SimKmsConnector:
    """Deterministic KMS (fdbserver/SimKmsConnector.actor.cpp): base
    secrets are HMACs of the domain id under a master seed, so every
    process in a simulation derives identical keys without coordination.
    Rotation bumps the per-domain base-id counter."""

    def __init__(self, master_seed: bytes = b"fdb-tpu-sim-kms"):
        self._seed = master_seed
        self._base_ids: dict[int, int] = {}
        self._revoked: set[tuple[int, int]] = set()

    def _secret(self, domain_id: int, base_id: int) -> bytes:
        msg = f"{domain_id}:{base_id}".encode()
        return hmac.new(self._seed, msg, hashlib.sha256).digest()

    def fetch_base_key(self, domain_id: int) -> tuple[int, bytes]:
        """Latest (base_id, base_secret) for a domain."""
        base_id = self._base_ids.setdefault(domain_id, 1)
        return base_id, self._secret(domain_id, base_id)

    def fetch_base_key_by_id(self, domain_id: int, base_id: int) -> bytes:
        if (domain_id, base_id) in self._revoked:
            raise KmsError(f"base key {base_id} of domain {domain_id} revoked")
        if base_id < 1:
            raise KmsError(f"bad base id {base_id} for domain {domain_id}")
        # Secrets are deterministic functions of (seed, domain, id): a
        # FRESH connector in a restarted process must serve generations
        # an earlier process rotated to, or an encrypted store becomes
        # unrecoverable across restart (code review r5). The rotation
        # counter is NOT floored here: by-id requests carry ids read
        # from UNVERIFIED on-disk headers, and letting a corrupted
        # header mutate which generation fetch_base_key serves next
        # would be untrusted bytes steering KMS state (second review
        # pass). A garbage id yields a key whose HMAC then fails —
        # loud, stateless.
        return self._secret(domain_id, base_id)

    def rotate(self, domain_id: int) -> int:
        """Force a new base key (the KMS-driven rotation path)."""
        self._base_ids[domain_id] = self._base_ids.get(domain_id, 1) + 1
        return self._base_ids[domain_id]

    def revoke(self, domain_id: int, base_id: int) -> None:
        self._revoked.add((domain_id, base_id))


class RestKmsConnector:
    """JSON-over-HTTP connector (fdbserver/RESTKmsConnector.actor.cpp):
    POST /getEncryptionKeys with {"domain_ids": [...]} or
    {"cipher_ids": [[domain, base_id], ...]} returns base keys hex-coded.
    Synchronous stdlib HTTP — the proxy calls it from an executor."""

    def __init__(self, endpoint: str):
        # endpoint: "host:port"
        self.endpoint = endpoint

    def _post(self, body: dict) -> dict:
        import http.client

        host, port = self.endpoint.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        try:
            conn.request(
                "POST", "/getEncryptionKeys", json.dumps(body),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise KmsError(f"KMS HTTP {resp.status}: {data[:200]!r}")
            return json.loads(data)
        finally:
            conn.close()

    def fetch_base_key(self, domain_id: int) -> tuple[int, bytes]:
        out = self._post({"domain_ids": [domain_id]})
        entry = out["keys"][0]
        return int(entry["base_id"]), bytes.fromhex(entry["secret"])

    def fetch_base_key_by_id(self, domain_id: int, base_id: int) -> bytes:
        out = self._post({"cipher_ids": [[domain_id, base_id]]})
        return bytes.fromhex(out["keys"][0]["secret"])

    def rotate(self, domain_id: int) -> int:
        out = self._post({"rotate": domain_id})
        return int(out["base_id"])


def serve_stub_kms(port: int = 0) -> tuple[object, int]:
    """A stub REST KMS backed by SimKmsConnector, for tests and local
    clusters (the reference's tests point RESTKmsConnector at exactly
    such a fake — fdbserver/workloads/RESTKmsWorkloads). Returns
    (http.server instance, bound port); caller shuts it down."""
    import http.server

    sim = SimKmsConnector()

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_POST(self):
            if self.path != "/getEncryptionKeys":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            try:
                if "rotate" in body:
                    out = {"base_id": sim.rotate(int(body["rotate"]))}
                elif "domain_ids" in body:
                    keys = []
                    for d in body["domain_ids"]:
                        bid, sec = sim.fetch_base_key(int(d))
                        keys.append({
                            "domain_id": d, "base_id": bid,
                            "secret": sec.hex(),
                        })
                    out = {"keys": keys}
                elif "cipher_ids" in body:
                    keys = []
                    for d, bid in body["cipher_ids"]:
                        sec = sim.fetch_base_key_by_id(int(d), int(bid))
                        keys.append({
                            "domain_id": d, "base_id": bid,
                            "secret": sec.hex(),
                        })
                    out = {"keys": keys}
                else:
                    raise KmsError("bad request")
                data = json.dumps(out).encode()
                self.send_response(200)
            except KmsError as e:
                data = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]
