"""System keyspace schema: keyServers / serverKeys encodings.

Capability match for fdbclient/SystemData.cpp's shard-location schema:
the reference persists, in the database itself,

* `\\xff/keyServers/<key>`  -> encoded (src team, dest team) — which
  servers own the shard beginning at <key> (dest non-empty only while
  a move is in flight), and
* `\\xff/serverKeys/<server>/<key>` -> ownership marker — the inverse
  map each storage server consults for its own ranges.

This build's authoritative map is the coordinated ShardMap object, so
the schema is served as a MATERIALIZED VIEW through the transaction
read path (the reference's readers — fdbcli `locate`, DD audits,
consistency checkers — see the same shape; the storage medium differs
and is documented here). Values use the repo's typed codec rather than
the reference's BinaryWriter bytes: byte-level parity would be format
translation, the capability is the queryable schema.
"""

from __future__ import annotations

import struct

KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"
SERVER_KEYS_PREFIX = b"\xff/serverKeys/"
SERVER_KEYS_END = b"\xff/serverKeys0"

_VAL_VERSION = 1


def key_servers_key(key: bytes) -> bytes:
    """keyServersKey(k): the schema key for the shard beginning at k."""
    return KEY_SERVERS_PREFIX + key


def key_servers_value(src: list[int], dest: list[int] = ()) -> bytes:
    """keyServersValue(src, dest): encoded source/destination teams."""
    out = [struct.pack("<BHH", _VAL_VERSION, len(src), len(dest))]
    for s in list(src) + list(dest):
        out.append(struct.pack("<q", s))
    return b"".join(out)


def decode_key_servers_value(value: bytes) -> tuple[list[int], list[int]]:
    if not value:
        return [], []
    ver, n_src, n_dest = struct.unpack_from("<BHH", value, 0)
    if ver != _VAL_VERSION:
        raise ValueError(f"unknown keyServers value version {ver}")
    ids = [
        struct.unpack_from("<q", value, 5 + 8 * i)[0]
        for i in range(n_src + n_dest)
    ]
    return ids[:n_src], ids[n_src:]


def server_keys_key(server: int, key: bytes) -> bytes:
    """serverKeysKey(serverID, k)."""
    return SERVER_KEYS_PREFIX + b"%d/" % server + key


SERVER_KEYS_TRUE = b"1"   # serverKeysTrue: the server owns from here
SERVER_KEYS_FALSE = b"0"  # serverKeysFalse: ownership ends here


def decode_server_keys_key(schema_key: bytes) -> tuple[int, bytes]:
    rest = schema_key[len(SERVER_KEYS_PREFIX):]
    sid, _, key = rest.partition(b"/")
    return int(sid), key


def materialize_key_servers(shard_map, begin: bytes = b"",
                            end: bytes = b"\xff") -> list[tuple[bytes, bytes]]:
    """The keyServers rows for shards intersecting [begin, end): one
    row per shard boundary, exactly the reference's layout (a row's
    key is the shard's begin key; its value names the owning team and
    any in-flight destination).

    Range-read contract: every returned schema key lies inside the
    requested [begin, end) — the shard STRADDLING `begin` is clamped to
    a row AT `begin` (krmGetRanges' alignment discipline,
    fdbclient/KeyRangeMap) rather than leaking a key below the bound,
    which would hand `get_range` callers rows outside their scan."""
    rows = []
    bounds = [b""] + list(shard_map.boundaries)
    for i, b in enumerate(bounds):
        shard_end = (
            shard_map.boundaries[i]
            if i < len(shard_map.boundaries) else b"\xff"
        )
        if shard_end <= begin or b >= end:
            continue
        b = max(b, begin)
        src = sorted(shard_map.owners[i])
        # in-flight destinations: the dual-tag window MoveKeys opens
        # while a shard streams to its new team (ShardMap.
        # extra_tag_ranges) — exactly the dest the reference's DD
        # audits read this schema for
        dest = sorted(
            tag
            for rb, re_, tag in getattr(shard_map, "extra_tag_ranges", [])
            if rb < shard_end and b < re_ and tag not in src
        )
        rows.append((key_servers_key(b), key_servers_value(src, dest)))
    return rows


def materialize_server_keys(shard_map, server: int) -> list[tuple[bytes, bytes]]:
    """The serverKeys rows for one server: boundary markers flipping
    TRUE at every owned range's begin and FALSE at its end (coalesced,
    the reference's run-length discipline)."""
    bounds = [b""] + list(shard_map.boundaries)
    rows = []
    owned_prev = False
    for i, b in enumerate(bounds):
        owned = server in shard_map.owners[i]
        if owned != owned_prev:
            rows.append((
                server_keys_key(server, b),
                SERVER_KEYS_TRUE if owned else SERVER_KEYS_FALSE,
            ))
            owned_prev = owned
    if owned_prev:
        rows.append((server_keys_key(server, b"\xff"), SERVER_KEYS_FALSE))
    return rows


def materialize_all_server_keys(shard_map) -> list[tuple[bytes, bytes]]:
    """serverKeys rows for EVERY server (the audit-style full scan) —
    sorted by schema key, i.e. by (server id as text, key)."""
    servers = sorted({s for team in shard_map.owners for s in team})
    rows = []
    for s in sorted(servers, key=lambda x: str(x)):
        rows.extend(materialize_server_keys(shard_map, s))
    return rows
