"""EncryptKeyProxy: the role between the KMS and every encrypting role.

Capability match for fdbserver/EncryptKeyProxy.actor.cpp: one process
per cluster talks to the KMS, derives record-encryption keys from base
secrets, caches them, and serves getLatestCipher / getCipherById to
storage servers, TLogs, backup workers and blob workers — so the KMS
sees one client and key material is derived in one place.

Derived keys (never base secrets) are what roles receive, exactly the
reference's split. Refresh: an encryption key older than
ENCRYPT_KEY_REFRESH_INTERVAL re-derives under a fresh salt (cheap, no
KMS trip); a KMS rotation (new base id) is picked up on the next
refresh. Old derived keys stay served for decryption until expired.
"""

from __future__ import annotations

import os
import threading
import time

from foundationdb_tpu.crypto.blob_cipher import (
    BlobCipherKey,
    BlobCipherKeyCache,
    derive_key,
)
from foundationdb_tpu.utils.knobs import SERVER_KNOBS


class EncryptKeyProxy:
    def __init__(self, kms, *, refresh_interval: float = None,
                 expire_interval: float = None, clock=None, entropy=None):
        self.kms = kms
        self.cache = BlobCipherKeyCache()
        # Injectable clock/entropy so a simulated cluster can pin both
        # (flowcheck determinism scope): pass `clock=sched.now` and a
        # seeded `entropy=rng.bytes` under the deterministic scheduler.
        # The wall-clock/urandom DEFAULTS are for real deployments only
        # (the in-cluster construction path is crypto/at_rest.
        # default_encryption, called from the real-process worker side,
        # cluster/multiprocess.py — outside the sim scope). flowcheck
        # flags calls, not references, so holding these as defaults
        # lints clean by design; sim-side callers must inject.
        self._clock = clock if clock is not None else time.time
        self._entropy = entropy if entropy is not None else os.urandom
        self.refresh_interval = (
            SERVER_KNOBS.ENCRYPT_KEY_REFRESH_INTERVAL
            if refresh_interval is None else refresh_interval
        )
        self.expire_interval = expire_interval  # None = never expire
        self.fetches = 0  # KMS round trips (observability/tests)
        self._refreshing: set[int] = set()
        self._lock = threading.Lock()

    # -- the role API (EncryptKeyProxyInterface.h) -----------------------

    def get_latest_cipher(self, domain_id: int) -> BlobCipherKey:
        """The key roles encrypt new records with. Re-derives under a
        fresh salt (and picks up KMS rotations) when the cached latest
        passes its refresh deadline."""
        try:
            return self.cache.latest(domain_id)
        except KeyError:
            pass
        base_id, secret = self.kms.fetch_base_key(domain_id)
        self.fetches += 1
        salt = self._entropy(16)
        now = self._clock()
        key = BlobCipherKey(
            domain_id=domain_id, base_id=base_id, salt=salt,
            key=derive_key(secret, domain_id, base_id, salt),
            refresh_at=now + self.refresh_interval,
            expire_at=(
                float("inf") if self.expire_interval is None
                else now + self.expire_interval
            ),
        )
        self.cache.insert(key)
        return key

    def get_latest_cipher_nonblocking(self, domain_id: int) -> BlobCipherKey:
        """Seal-path variant that NEVER blocks on the KMS once a domain
        is warm: a stale (past-refresh) key is still used while one
        background thread refreshes it — the reference's refresh is a
        background actor too (EncryptKeyProxy.actor.cpp
        refreshEncryptionKeysCore); a commit path must not stall up to
        the KMS timeout under the apply lock (code review r5). Blocks
        only on the very first use of a domain (nothing cached at all —
        role init prefetches to avoid even that)."""
        key = self.cache.latest_any(domain_id)
        if key is None or not key.usable_for_decrypt():
            # nothing cached, or the cached latest passed its EXPIRE
            # deadline — sealing under an expired key would produce
            # records the same process refuses to read back (code
            # review r5): block for a fresh key, correctness over
            # latency
            return self.get_latest_cipher(domain_id)
        if key.usable_for_encrypt():
            return key
        with self._lock:
            spawn = domain_id not in self._refreshing
            if spawn:
                self._refreshing.add(domain_id)
        if spawn:
            def refresh():
                try:
                    self.get_latest_cipher(domain_id)
                except Exception as e:
                    # keep sealing under the stale key; retry next call —
                    # but a failing KMS must be visible, not silent
                    from foundationdb_tpu.utils.trace import (
                        SEV_WARN,
                        TraceEvent,
                    )

                    TraceEvent("EKPRefreshFailed", severity=SEV_WARN) \
                        .detail("Domain", domain_id) \
                        .detail("Err", repr(e)).log()
                finally:
                    with self._lock:
                        self._refreshing.discard(domain_id)

            threading.Thread(target=refresh, daemon=True).start()
        return key

    def get_cipher_by_id(self, domain_id: int, base_id: int,
                         salt: bytes) -> BlobCipherKey:
        """The key a stored record's header names (decryption path).
        Cache miss goes to the KMS by id — the reference's
        getEncryptCipherKeys-by-baseCipherId path. An EXPIRED key is
        not a miss: retirement stands; re-deriving it would make
        expire_interval unenforceable. (Scope: in-process expiry is a
        cache policy — a RESTARTED process re-fetches unless the KMS
        itself revoked the base id (kms.revoke), which is the
        cross-restart retirement mechanism; by-id keys re-derived here
        inherit expire_interval rather than living forever.)"""
        from foundationdb_tpu.crypto.blob_cipher import CipherKeyExpiredError

        try:
            return self.cache.lookup(domain_id, base_id, salt)
        except CipherKeyExpiredError:
            raise
        except KeyError:
            secret = self.kms.fetch_base_key_by_id(domain_id, base_id)
            self.fetches += 1
            key = BlobCipherKey(
                domain_id=domain_id, base_id=base_id, salt=salt,
                key=derive_key(secret, domain_id, base_id, salt),
                refresh_at=0.0,  # by-id keys serve decryption only
                expire_at=(
                    float("inf") if self.expire_interval is None
                    else self._clock() + self.expire_interval
                ),
            )
            self.cache.insert(key, latest=False)
            return key
