"""Cluster assembly: every role wired into one runnable transaction system.

The single-process analog of the reference's simulated cluster
(fdbserver/SimulatedCluster.actor.cpp): Sequencer (master), GrvProxy,
CommitProxies, Resolvers (each wrapping the TPU conflict kernel), one
TLog, and key-range-sharded StorageServers — connected by the same
version chains the real system uses. The client stack
(cluster/client.py) runs real transactions against it.

Role recruitment order mirrors recovery (fdbserver/ClusterRecovery.
actor.cpp): resolvers get the master's initial batch (prev_version < 0),
tlog/storage start at the recovery version, then proxies open for
business.
"""

from __future__ import annotations

import dataclasses

from foundationdb_tpu.cluster.client import Database
from foundationdb_tpu.cluster.commit_proxy import CommitProxy, KeyPartition
from foundationdb_tpu.cluster.grv_proxy import GrvProxy
from foundationdb_tpu.cluster.sequencer import Sequencer
from foundationdb_tpu.cluster.storage import StorageServer
from foundationdb_tpu.cluster.tlog import TLog
from foundationdb_tpu.config import KernelConfig, TEST_CONFIG
from foundationdb_tpu.models.types import ResolveTransactionBatchRequest
from foundationdb_tpu.resolver import Resolver
from foundationdb_tpu.runtime.flow import Scheduler, all_of


@dataclasses.dataclass
class ClusterConfig:
    n_commit_proxies: int = 1
    n_grv_proxies: int = 1          # v0: one GRV proxy
    n_resolvers: int = 1
    n_storage: int = 2
    # replicas per shard (storage teams); 1 = no replication
    replication_factor: int = 1
    # transaction log replicas (LogSystem); 1 = single log
    n_tlogs: int = 1
    # satellite log replicas (a second failure domain INSIDE the primary
    # region): commits ack only after satellites durably hold the
    # mutation stream, so a whole-primary-DC death loses nothing once a
    # remote region recovers the suffix from them (RPO=0 —
    # ha-write-path.rst + TagPartitionedLogSystem.actor.cpp)
    n_satellite_logs: int = 0
    # coordination quorum size (CoordinatedState/LeaderElection); recovery
    # requires a majority of these alive
    n_coordinators: int = 3
    # optional failure-domain topology: server id -> LocalityData, plus a
    # replication policy (cluster/locality.py) that storage teams must
    # satisfy (PolicyAcross zones/DCs — fdbrpc/ReplicationPolicy.cpp)
    storage_localities: dict = None
    replication_policy: object = None
    # TSS mirror pairs (design/tss.md): TSS i mirrors storage server i
    # for i < n_tss — same log tag, so identical content by
    # construction; clients duplicate a read sample for comparison
    n_tss: int = 0
    # When set, role-to-role calls go through a SimNetwork with this seed
    # (deterministic latency; clogging/partition fault injection).
    sim_seed: int = None
    resolver_boundaries: list = None  # len n_resolvers-1; default even bytes
    storage_boundaries: list = None   # len n_storage-1
    # Versions advance at ~1e6/s of (virtual) time (Sequencer), so the MVCC
    # window must be the reference's time-window equivalent (5s = 5e6
    # versions, fdbclient/ServerKnobs.cpp:43), not the unit-test default.
    # Keys get headroom over the unit-test config (point-write conflict
    # ranges append \x00 to the key).
    kernel_config: KernelConfig = TEST_CONFIG.scaled(
        window_versions=5_000_000, max_key_bytes=16
    )
    # resolver_backend knob: "tpu" (the JAX kernel) or "cpu" (host model);
    # None defers to SERVER_KNOBS.RESOLVER_BACKEND
    resolver_backend: str = None
    commit_batch_interval: float = 0.005
    window_versions: int = None      # default: kernel_config.window_versions
    # periodic per-role trace_counters flush cadence (virtual seconds) —
    # the reference's CounterCollection::traceCounters loop, scaled to
    # sim-seed time horizons (the reference default is 5s wall)
    counter_flush_interval: float = 1.0

    def __post_init__(self):
        if self.replication_policy is not None:
            if self.storage_localities is None:
                raise ValueError("replication_policy requires storage_localities")
            bad = [s for s in self.storage_localities if not (
                isinstance(s, int) and 0 <= s < self.n_storage)]
            if bad:
                raise ValueError(
                    f"storage_localities ids {bad} out of range for "
                    f"n_storage={self.n_storage}"
                )
            missing = [s for s in range(self.n_storage)
                       if s not in self.storage_localities]
            if missing:
                # teams are built from localities keys; an uncovered
                # server would silently own zero shards forever
                raise ValueError(
                    f"storage_localities missing ids {missing}: every "
                    f"server needs a declared failure domain"
                )
            if self.replication_policy.min_replicas != self.replication_factor:
                raise ValueError(
                    f"replication_factor={self.replication_factor} != "
                    f"policy.min_replicas="
                    f"{self.replication_policy.min_replicas}: team size is "
                    "the policy's — make them agree explicitly"
                )
        if self.replication_factor > self.n_storage:
            raise ValueError(
                f"replication_factor {self.replication_factor} > "
                f"n_storage {self.n_storage}"
            )
        if self.resolver_boundaries is None:
            self.resolver_boundaries = _even_boundaries(self.n_resolvers)
        if self.storage_boundaries is None:
            self.storage_boundaries = _even_boundaries(self.n_storage)
        if self.window_versions is None:
            self.window_versions = self.kernel_config.window_versions


def _even_boundaries(n: int) -> list:
    """n-way even split of the one-byte-prefix keyspace."""
    return [bytes([int(256 * (i + 1) / n)]) for i in range(n - 1)]


class Cluster:
    def __init__(self, sched: Scheduler, config: ClusterConfig = None):
        self.sched = sched
        self.config = config or ClusterConfig()
        cfg = self.config

        from foundationdb_tpu.cluster.shardmap import ShardMap

        self.sequencer = Sequencer(sched)
        self.key_resolvers = KeyPartition(list(cfg.resolver_boundaries))
        self.key_servers = ShardMap.even(
            list(cfg.storage_boundaries),
            replication=cfg.replication_factor,
            n_servers=cfg.n_storage,
            localities=cfg.storage_localities,
            policy=cfg.replication_policy,
        )
        self.resolvers = [
            Resolver(
                sched,
                cfg.kernel_config,
                resolver_id=i,
                resolver_count=cfg.n_resolvers,
                commit_proxy_count=cfg.n_commit_proxies,
                backend=cfg.resolver_backend,
            )
            for i in range(cfg.n_resolvers)
        ]
        from foundationdb_tpu.cluster.logsystem import LogSystem

        self.tlog = LogSystem(
            sched, cfg.n_tlogs, n_satellites=cfg.n_satellite_logs
        )
        self.storage_servers = [
            StorageServer(
                sched, self.tlog, tag=s, window_versions=cfg.window_versions,
                # per-server byteSample seed, derived from the sim seed:
                # deterministic per (seed, tag), distinct across servers
                sample_seed=((cfg.sim_seed or 0) << 8) ^ s,
            )
            for s in range(cfg.n_storage)
        ]
        # TSS mirrors: same tag as their paired server => the
        # tag-partitioned log delivers them the identical mutation
        # stream (cluster/tss.py; fdbserver/storageserver.actor.cpp TSS)
        self.tss_servers = {
            s: StorageServer(
                sched, self.tlog, tag=s,
                window_versions=cfg.window_versions,
                consumer=f"tss{s}",
            )
            for s in range(cfg.n_tss)
        }
        # failure-monitor view of storage liveness (clients skip dead
        # replicas; see fdbrpc/FailureMonitor.actor.cpp)
        self.storage_live = [True] * cfg.n_storage
        self.txn_state_store: dict[bytes, bytes] = {}

        self.net = None
        if cfg.sim_seed is not None:
            from foundationdb_tpu.sim.network import SimNetwork

            self.net = SimNetwork(sched, seed=cfg.sim_seed)

        from foundationdb_tpu.cluster.coordination import Coordinator

        self.coordinators = [
            Coordinator(f"coord{i}") for i in range(cfg.n_coordinators)
        ]
        # Dynamic-knob quorum registers (fdbserver/ConfigNode.actor.cpp):
        # a SEPARATE generation-disciplined register per coordinator host
        # — the leader-election register above holds the LeaderLease and
        # cannot double as the knob store. Killed/revived with their
        # coordinator (colocated role).
        self.config_nodes = [
            Coordinator(f"confignode{i}") for i in range(cfg.n_coordinators)
        ]

        self.build_proxies(epoch=1)
        from foundationdb_tpu.cluster.balancer import ResolutionBalancer
        from foundationdb_tpu.cluster.ratekeeper import Ratekeeper

        self.balancer = ResolutionBalancer(
            sched, self.resolvers, self.key_resolvers, self.commit_proxies
        )
        # The multi-input admission controller: every saturation sensor
        # the PR-7 telemetry substrate exposes feeds the control law —
        # tlog queue bytes, storage version lag, resolver occupancy +
        # queue depth, proxy queue depth, and the GRV proxies' observed
        # admission rate. Proxy/GRV lists are SUPPLIERS because recovery
        # rebuilds the proxy generation (build_proxies reassigns).
        self.ratekeeper = Ratekeeper(
            sched, self.sequencer, self.storage_servers,
            liveness=self.storage_live,
            tlog_system=self.tlog,
            resolvers=self.resolvers,
            proxies=lambda: self.commit_proxies,
            grv_proxies=lambda: [self.grv_proxy],
        )
        self.grv_proxy = GrvProxy(sched, self.sequencer, ratekeeper=self.ratekeeper)
        # What clients actually talk to (network-wrapped under simulation).
        self.client_storages = [
            self._wrapped(
                "client", f"storage{s}", ss, ["get_value", "get_key_values"]
            )
            for s, ss in enumerate(self.storage_servers)
        ]
        self.client_tss = {
            s: self._wrapped(
                "client", f"tss{s}", ss, ["get_value", "get_key_values"]
            )
            for s, ss in self.tss_servers.items()
        }
        from foundationdb_tpu.cluster.data_distribution import DataDistributor
        from foundationdb_tpu.cluster.failure_monitor import FailureMonitor
        from foundationdb_tpu.cluster.recovery import ClusterController

        # Address-level failure monitor (fdbrpc/FailureMonitor.actor.cpp):
        # pings every storage endpoint (through the SimNetwork when one
        # exists, so partitions look like death from the controller's
        # vantage) and maintains the shared storage_live view every
        # consumer reads. Client requests that hit a dead process report
        # it immediately (the loadBalance fast path).
        self.failure_monitor = FailureMonitor(sched)
        for s, ss in enumerate(self.storage_servers):
            self.failure_monitor.register(
                f"storage{s}",
                self._wrapped("cc", f"storage{s}", ss, ["ping"]).ping,
            )

        def _on_liveness_change(addr: str, failed: bool) -> None:
            if addr.startswith("storage"):
                self.storage_live[int(addr[len("storage"):])] = not failed

        self.failure_monitor.on_change(_on_liveness_change)
        self.controller = ClusterController(self)
        self.data_distributor = DataDistributor(self)
        self._started = False
        self._next_client_id = 0
        self._metrics_task = None

    async def _trace_counters_loop(self) -> None:
        """Periodic per-role counter flush on the VIRTUAL clock
        (CounterCollection::traceCounters): every role's counters land
        in the active TraceLog as structured events, so a soak or
        wire-pipeline run carries continuous per-role telemetry —
        not just bench.py's end-of-run ledger. Counter values are
        deterministic per (seed, perturb), so traced output stays
        bit-reproducible; wall-clock stage samples deliberately stay
        out of these events (see KernelStageMetrics)."""
        from foundationdb_tpu.utils import trace as _trace

        while True:
            await self.sched.delay(self.config.counter_flush_interval)
            _trace.trace_counters(
                _trace.g_trace, "GrvProxyMetrics", "grv_proxy0",
                self.grv_proxy.counters,
            )
            for p in self.commit_proxies:
                _trace.trace_counters(
                    _trace.g_trace, "ProxyMetrics", p.proxy_id, p.counters
                )
            for r in self.resolvers:
                _trace.trace_counters(
                    _trace.g_trace, "ResolverMetrics",
                    f"resolver{r.resolver_id}", r.counters,
                )
                cs = r.conflict_set
                if cs is not None and getattr(cs, "metrics", None) is not None:
                    _trace.trace_counters(
                        _trace.g_trace, "ResolverKernelMetrics",
                        f"resolver{r.resolver_id}", cs.metrics.counters,
                    )

    def next_client_id(self) -> int:
        """Monotonic per-cluster client-handle id (the idempotency-id
        nonce component — cluster/client.py Database)."""
        self._next_client_id += 1
        return self._next_client_id

    def _wrapped(self, src, dst, obj, methods):
        if self.net is None:
            return obj
        return self.net.wrap(src, dst, obj, methods)

    def build_proxies(self, epoch: int) -> None:
        """(Re)recruit the commit-proxy generation (recovery re-enters)."""
        cfg = self.config
        self.commit_proxies = [
            CommitProxy(
                self.sched,
                f"proxy{p}.{epoch}" if epoch > 1 else f"proxy{p}",
                self.sequencer,
                [
                    self._wrapped(f"proxy{p}", f"resolver{i}", r, ["resolve"])
                    for i, r in enumerate(self.resolvers)
                ],
                self._wrapped(f"proxy{p}", "tlog0", self.tlog, ["commit"]),
                self.key_resolvers,
                self.key_servers,
                epoch=epoch,
                batch_interval=cfg.commit_batch_interval,
                # a batch must fit the kernel's static txn capacity
                max_batch_txns=cfg.kernel_config.max_txns,
                on_state_mutation=self._apply_state_mutation,
                txn_state_view=self.txn_state_store,
            )
            for p in range(cfg.n_commit_proxies)
        ]

    def reboot_storage(self, s: int) -> None:
        """Kill storage server s and bring up a replacement from its durable
        state — the SaveAndKill/restart-test path (SURVEY.md §4): the new
        process resumes pulling the log from its durable version."""
        old = self.storage_servers[s]
        old.stop()
        new = StorageServer(
            self.sched, self.tlog, tag=s,
            window_versions=self.config.window_versions,
            sample_seed=((self.config.sim_seed or 0) << 8) ^ s,
        )
        new.restore(old.snapshot())
        self.storage_servers[s] = new
        self.storage_live[s] = True
        # the replacement process answers pings now; re-point the
        # monitor's probe at it and clear the failure state
        self.failure_monitor.register(
            f"storage{s}",
            self._wrapped("cc", f"storage{s}", new, ["ping"]).ping,
        )
        self.failure_monitor.report_alive(f"storage{s}")
        if self.net is None:
            self.client_storages[s] = new
        else:
            self.client_storages[s] = self.net.wrap(
                "client", f"storage{s}", new, ["get_value", "get_key_values"]
            )
        if self._started:
            new.start()

    def kill_coordinator(self, i: int) -> None:
        # the ConfigNode register is colocated with the coordinator
        # (one host in the reference deployment): it dies with it
        self.coordinators[i].kill()
        self.config_nodes[i].kill()

    def revive_coordinator(self, i: int) -> None:
        self.coordinators[i].revive()
        self.config_nodes[i].revive()

    def kill_tlog(self, i: int) -> None:
        """Mark a log replica dead; commits continue on the survivors."""
        self.tlog.kill(i)

    def crash_reboot_tlog(self, i: int, rng=None) -> None:
        """Power-loss + DiskQueue recovery scan + peer catch-up for one
        log replica (sim disk stack — AsyncFileNonDurable semantics)."""
        self.tlog.crash_and_reboot(i, rng)

    def kill_storage(self, s: int) -> None:
        """Kill a storage server with an immediate failure report (the
        path a client's errored request takes); reads fail over to team
        peers at once."""
        self.storage_servers[s].stop()
        self.failure_monitor.report_failed(f"storage{s}")

    def kill_storage_silent(self, s: int) -> None:
        """Kill a storage server WITHOUT telling anyone: only the
        failure monitor's ping loop (or a client's errored read) can
        discover it — the detection path the reference exercises with
        machine kills (fdbrpc/FailureMonitor.actor.cpp)."""
        self.storage_servers[s].stop()

    def _apply_state_mutation(self, m) -> None:
        from foundationdb_tpu.models.types import apply_state_mutation

        apply_state_mutation(self.txn_state_store, m)

    async def _bootstrap(self) -> None:
        # The master's initial resolver batch (prev_version < 0) — creates
        # the master entry every resolver's proxy map needs.
        futs = []
        for r in self.resolvers:
            futs.append(
                self.sched.spawn(
                    r.resolve(
                        ResolveTransactionBatchRequest(
                            prev_version=-1,
                            version=0,
                            last_received_version=-1,
                            transactions=[],
                        )
                    )
                ).done
            )
        await all_of(futs)

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self.sched.run_until(self.sched.spawn(self._bootstrap()).done)
        for ss in self.storage_servers:
            ss.start()
        for ss in self.tss_servers.values():
            ss.start()
        for cp in self.commit_proxies:
            cp.start()
        self.grv_proxy.start()
        self.ratekeeper.start()
        self.balancer.start()
        self.controller.start()
        self.data_distributor.start()
        self.failure_monitor.start()
        self._metrics_task = self.sched.spawn(
            self._trace_counters_loop(), name="metrics-flush"
        )

    def stop(self) -> None:
        if self._metrics_task is not None:
            self._metrics_task.cancel()
            self._metrics_task = None
        self.failure_monitor.stop()
        self.data_distributor.stop()
        self.controller.stop()
        self.balancer.stop()
        for ss in self.storage_servers:
            ss.stop()
        for ss in self.tss_servers.values():
            ss.stop()
        for cp in self.commit_proxies:
            cp.stop()
        self.grv_proxy.stop()
        self.ratekeeper.stop()
        self._started = False

    def database(self) -> Database:
        return Database(self)


def open_cluster(config: ClusterConfig = None, *, sched: Scheduler = None):
    sched = sched or Scheduler(sim=True)
    cluster = Cluster(sched, config)
    cluster.start()
    return sched, cluster, cluster.database()
