"""Client API: Database / Transaction with read-your-writes.

Behavioral mirror of the reference client stack:

* `Transaction` (fdbclient/NativeAPI.actor.cpp): lazy GRV
  (getReadVersion -> GRV proxy batch), reads routed to the storage shard
  owning the key, commit via a commit proxy, retry loop with backoff
  (`on_error`).
* Read-your-writes (fdbclient/ReadYourWrites.actor.cpp / WriteMap.h):
  uncommitted writes overlay reads — a `get` of a key this txn set
  returns the new value without adding phantom conflicts; range reads
  merge the write map over the storage snapshot.
* Conflict ranges (fdbclient/RYWIterator.cpp semantics): point reads add
  [k, k+\\x00) read conflicts; range reads add [begin, end); sets add
  point write conflicts; clears add range write conflicts — matching
  CommitTransactionRef's contract (fdbclient/CommitTransaction.h).
"""

from __future__ import annotations

import bisect
from typing import Optional

from foundationdb_tpu.cluster.commit_proxy import (
    CommitUnknownResult,
    NotCommitted,
    TransactionTooOldError,
)
from foundationdb_tpu.cluster.grv_proxy import (
    GrvProxyFailedError,
    GrvThrottledError,
)
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.utils import commit_debug as _cd
from foundationdb_tpu.utils import trace as _trace


def key_after(k: bytes) -> bytes:
    return k + b"\x00"


class WriteMap:
    """Uncommitted writes: sorted clear ranges + point sets + pending
    atomics over unknown bases (WriteMap.h)."""

    def __init__(self):
        self.sets: dict[bytes, bytes] = {}
        self.clears: list[tuple[bytes, bytes]] = []  # disjoint, sorted
        # key -> [(op, param)] applied over the server value at read time
        self.atomics: dict[bytes, list] = {}

    def set(self, k: bytes, v: bytes) -> None:
        self.sets[k] = v
        self.atomics.pop(k, None)

    def clear(self, b: bytes, e: bytes) -> None:
        for k in [k for k in self.sets if b <= k < e]:
            del self.sets[k]
        for k in [k for k in self.atomics if b <= k < e]:
            del self.atomics[k]
        merged = [(b, e)]
        for cb, ce in self.clears:
            if ce < b or cb > e:  # disjoint (touching ranges merge)
                merged.append((cb, ce))
            else:
                merged[0] = (min(merged[0][0], cb), max(merged[0][1], ce))
        self.clears = sorted(merged)

    def lookup(self, k: bytes) -> tuple[bool, Optional[bytes]]:
        """(known, value): known=True if this txn wrote/cleared k."""
        if k in self.sets:
            return True, self.sets[k]
        for cb, ce in self.clears:
            if cb <= k < ce:
                return True, None
        return False, None

    def overlay(self, items: list[tuple[bytes, bytes]], b: bytes, e: bytes):
        """Merge the write map over a storage snapshot of [b, e)."""
        from foundationdb_tpu.utils.atomic import apply_atomic

        out = {k: v for k, v in items}
        for cb, ce in self.clears:
            for k in [k for k in out if cb <= k < ce]:
                del out[k]
        for k, v in self.sets.items():
            if b <= k < e:
                out[k] = v
        for k, ops in self.atomics.items():
            if b <= k < e:
                v = out.get(k)
                for op, param in ops:
                    v = apply_atomic(op, v, param)
                if v is None:
                    out.pop(k, None)
                else:
                    out[k] = v
        return sorted(out.items())


class Transaction:
    def __init__(self, db: "Database", tag: str = None):
        self.db = db
        #: optional transaction tag: GRV requests carrying it are metered
        #: against the Ratekeeper's per-tag quota (tag throttling)
        self.tag = tag
        self._read_version: Optional[int] = None
        # in-flight GRV request (prefetch_read_version): issued without
        # awaiting so read-set building overlaps the GRV batch roundtrip
        self._grv_promise = None
        self._grv_span = None
        self.writes = WriteMap()
        self.mutations: list = []
        self.read_conflicts: list[tuple[bytes, bytes]] = []
        self.write_conflicts: list[tuple[bytes, bytes]] = []
        self.report_conflicting_keys = False
        self.committed_version: Optional[int] = None
        self._versionstamp: Optional[bytes] = None
        self.idempotency_id: Optional[bytes] = None
        # set by the DR agent: its own applies may write while the
        # database is DR-locked (cluster/dr.py)
        self.dr_bypass = False
        # Commit-path telemetry (the reference's debugTransaction): with
        # db.tracing on, every transaction carries a DETERMINISTIC debug
        # id — (origin, client, seq), the idempotency-nonce discipline —
        # and emits the NativeAPI.* trace_batch micro-events the
        # commit_debug reconstructor joins on.
        self.debug_id: Optional[str] = db.next_debug_id() if db.tracing else None

    # -- reads ------------------------------------------------------------

    def prefetch_read_version(self) -> None:
        """Issue the GRV request NOW without awaiting it — the client-
        side GRV/read-set overlap (the reference NativeAPI's eager
        readVersionFuture): the request joins the GRV proxy's current
        batch while the caller keeps building its read set / RYW
        overlay, and the first read awaits the in-flight reply instead
        of paying the whole GRV roundtrip serially. Idempotent; a
        no-op once a read version is pinned."""
        if self._read_version is not None or self._grv_promise is not None:
            return
        gspan = None
        if self.debug_id is not None:
            # span-threaded GRV: the span opens at ISSUE time so the
            # waterfall shows the overlapped window, and finishes when
            # the reply is consumed (get_read_version)
            from foundationdb_tpu.utils.spans import Span

            gspan = Span(
                "NativeAPI.getConsistentReadVersion",
                clock=self.db.sched.now,
            )
            _trace.g_trace_batch.add_event(
                "TransactionDebug", self.debug_id, _cd.GRV_BEFORE
            )
        p = self.db.grv_proxy.get_read_version(self.tag)
        if self.debug_id is not None:
            p.debug_id = self.debug_id  # rides to the batcher
            p.span_ctx = gspan.context
        self._grv_promise = p
        self._grv_span = gspan

    async def get_read_version(self) -> int:
        if self._read_version is None:
            self.prefetch_read_version()
            # ownership transfer, not a snapshot: the in-flight promise
            # and its span are POPPED before the await precisely so no
            # concurrent consumer can double-await them; the fields are
            # deliberately not re-read after the wait.
            p, self._grv_promise = self._grv_promise, None
            gspan, self._grv_span = self._grv_span, None  # flowcheck: ignore[flow.stale-read-across-wait]
            try:
                self._read_version = await p.future
                if self.debug_id is not None:
                    _trace.g_trace_batch.add_event(
                        "TransactionDebug", self.debug_id, _cd.GRV_AFTER
                    )
            finally:
                if gspan is not None:
                    gspan.finish()
        return self._read_version

    async def get(self, key: bytes, *, snapshot: bool = False) -> Optional[bytes]:
        if key.startswith(b"\xff\xff"):
            # the special key space: virtual management reads
            # (fdbclient/SpecialKeySpace.actor.cpp)
            return self.db.special_key(key)
        known, val = self.writes.lookup(key)
        if not known:
            rv = await self.get_read_version()
            val = await self.db.read_value(key, rv)
            if not snapshot:
                self.read_conflicts.append((key, key_after(key)))
        # RYW over atomics on an unknown base: apply pending ops to the
        # snapshot value (ReadYourWrites' read-modify view).
        from foundationdb_tpu.utils.atomic import apply_atomic

        for op, param in self.writes.atomics.get(key, []):
            val = apply_atomic(op, val, param)
        return val

    @staticmethod
    def _clip_rows(rows, limit: int, reverse: bool):
        """Apply limit+reverse to a fully-materialized row list: a
        reverse scan walks from `end` downward, so the limit keeps the
        HIGHEST keys and they return in descending order
        (Transaction::getRange reverse semantics)."""
        if reverse:
            sel = rows[len(rows) - limit:] if limit < len(rows) else rows
            return list(reversed(sel))
        return rows[:limit]

    async def get_range(
        self, begin: bytes, end: bytes, *, limit: int = 1 << 30,
        snapshot: bool = False, reverse: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        from foundationdb_tpu.cluster import system_data as SD

        if limit <= 0:
            return []

        for mod_b, mod_e in (
            (SD.KEY_SERVERS_PREFIX, SD.KEY_SERVERS_END),
            (SD.SERVER_KEYS_PREFIX, SD.SERVER_KEYS_END),
        ):
            if begin < mod_e and mod_b < end and not (
                mod_b <= begin and end <= mod_e
            ):
                # module-bounds discipline (the reference's
                # SpecialKeySpace CROSS_MODULE_READ error): a scan may
                # not straddle a materialized schema module — silently
                # mixing schema rows with stored rows would drop data
                raise ValueError(
                    f"range [{begin!r}, {end!r}) crosses the "
                    f"materialized schema module [{mod_b!r}, {mod_e!r}); "
                    "query within the module bounds"
                )
        if begin.startswith(SD.KEY_SERVERS_PREFIX):
            # the shard-location schema (SystemData.cpp keyServersKeys):
            # materialized from the authoritative shard map
            strip = len(SD.KEY_SERVERS_PREFIX)
            rows = SD.materialize_key_servers(
                self.db.cluster.key_servers,
                begin[strip:],
                end[strip:] if end.startswith(SD.KEY_SERVERS_PREFIX)
                else b"\xff",
            )
            return self._clip_rows(rows, limit, reverse)
        if begin.startswith(SD.SERVER_KEYS_PREFIX):
            rows = SD.materialize_all_server_keys(
                self.db.cluster.key_servers
            )
            rows = [r for r in rows if begin <= r[0] < end]
            return self._clip_rows(rows, limit, reverse)
        rv = await self.get_read_version()
        items = await self.db.read_range(begin, end, rv)
        full = self.writes.overlay(items, begin, end)
        truncated = limit < len(full)
        merged = self._clip_rows(full, limit, reverse)
        if not snapshot:
            # The reference narrows the conflict range to the keys actually
            # read when a limit stops the scan early; with a full scan it is
            # [begin, end). A reverse scan walks from `end` downward, so
            # its observed window is [lowest returned key, end).
            if not truncated:
                self.read_conflicts.append((begin, end))
            elif reverse:
                self.read_conflicts.append((merged[-1][0], end))
            else:
                self.read_conflicts.append((begin, key_after(merged[-1][0])))
        return merged

    async def watch(self, key: bytes):
        """Watch `key`: returns a Future firing when its value changes from
        what this transaction observes (Transaction::watch semantics —
        registered against the owning storage server via the same
        network-wrapped endpoint as reads)."""
        value = await self.get(key, snapshot=True)
        return self.db.storage_for(key).watch(key, value)

    # -- writes -----------------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        self.writes.set(key, value)
        self.mutations.append(("set", key, value))
        self.write_conflicts.append((key, key_after(key)))

    def clear(self, key: bytes) -> None:
        self.clear_range(key, key_after(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self.writes.clear(begin, end)
        self.mutations.append(("clear", begin, end))
        self.write_conflicts.append((begin, end))

    def atomic_op(self, op: str, key: bytes, param: bytes) -> None:
        """Atomic read-modify-write mutation (Transaction::atomicOp;
        MutationRef types — utils/atomic.py has the semantics)."""
        from foundationdb_tpu.utils.atomic import ATOMIC_OPS, apply_atomic

        if op not in ATOMIC_OPS:
            raise ValueError(f"unknown atomic op {op!r}")
        known, val = self.writes.lookup(key)
        if known:
            new = apply_atomic(op, val, param)
            if new is None:
                self.writes.clear(key, key_after(key))
            else:
                self.writes.set(key, new)
        else:
            self.writes.atomics.setdefault(key, []).append((op, param))
        self.mutations.append(("atomic", op, key, param))
        self.write_conflicts.append((key, key_after(key)))

    def add(self, key: bytes, value: int, width: int = 8) -> None:
        """fdb's ADD convenience: little-endian integer add."""
        self.atomic_op("add", key, value.to_bytes(width, "little", signed=True))

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self.read_conflicts.append((begin, end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self.write_conflicts.append((begin, end))

    def set_versionstamped_key(
        self, prefix: bytes, suffix: bytes, value: bytes
    ) -> None:
        """SET_VERSIONSTAMPED_KEY: final key = prefix + 10-byte commit
        versionstamp + suffix, assigned at commit (MutationRef::
        SetVersionstampedKey)."""
        self.mutations.append(("vs_key", prefix, suffix, value))
        self.write_conflicts.append((prefix, prefix + b"\xff" * 11))

    def set_versionstamped_value(self, key: bytes, value_prefix: bytes) -> None:
        """SET_VERSIONSTAMPED_VALUE: value gets the stamp appended."""
        self.mutations.append(("vs_value", key, value_prefix))
        self.writes.atomics.pop(key, None)
        self.write_conflicts.append((key, key_after(key)))

    @property
    def versionstamp(self) -> Optional[bytes]:
        """The commit versionstamp (after a successful commit)."""
        return self._versionstamp

    def set_idempotency_id(self, ident: Optional[bytes] = None) -> bytes:
        """AUTOMATIC_IDEMPOTENCY (fdbclient/IdempotencyId.actor.cpp): the
        commit also records `\\xff/idmp/<id>`, so a retry after
        commit_unknown_result can detect that the first attempt really
        committed instead of applying twice. The default id is the
        Database's deterministic per-client nonce, never entropy — a
        simulated run replays the exact same ids (the flowcheck
        determinism contract)."""
        if ident is None:
            ident = self.db.next_idempotency_id()
        self.idempotency_id = ident
        return ident

    # -- commit -----------------------------------------------------------

    async def commit(self) -> int:
        if not self.mutations and not self.write_conflicts:
            # Read-only transactions commit client-side at the read version
            # (Transaction::commit fast path).
            self.committed_version = await self.get_read_version()
            return self.committed_version
        if getattr(self.db, "dr_locked", False) and not self.dr_bypass:
            # databaseLocked: a DR destination refuses ordinary commits
            # (the reference checks \xff/dbLocked on every commit)
            from foundationdb_tpu.cluster.dr import DestinationLockedError

            raise DestinationLockedError(
                "database is a DR destination; writes are locked"
            )
        rv = await self.get_read_version()
        mutations = list(self.mutations)
        if self.idempotency_id is not None:
            mutations.append(
                ("set", b"\xff/idmp/" + self.idempotency_id, b"\x01")
            )
        ctr = CommitTransaction(
            read_conflict_ranges=_dedup(self.read_conflicts),
            write_conflict_ranges=_dedup(self.write_conflicts),
            read_snapshot=rv,
            report_conflicting_keys=self.report_conflicting_keys,
            mutations=mutations,
            lock_aware=self.dr_bypass,
        )
        ctr.validate()
        # _pin_proxy: targeted fencing (backup's stream barrier) must
        # hit a SPECIFIC proxy — round-robin adjacency is not a
        # guarantee under concurrent traffic
        proxy = getattr(self, "_pin_proxy", None) or self.db.commit_proxy()
        if self.debug_id is None:
            commit_id = await proxy.commit(ctr).future
        else:
            # span-threaded commit (Tracing.actor.cpp): the client span
            # context rides the request; the proxy's commitBatch span
            # parents on it, the resolvers' on the batch span — one
            # trace from transaction origin to resolution
            from foundationdb_tpu.utils.spans import Span

            ctr.debug_id = self.debug_id
            with Span("NativeAPI.commit", clock=self.db.sched.now) as span:
                ctr.span = span.context.as_tuple()
                _trace.g_trace_batch.add_event(
                    "CommitDebug", self.debug_id, _cd.COMMIT_BEFORE
                )
                commit_id = await proxy.commit(ctr).future
                _trace.g_trace_batch.add_event(
                    "CommitDebug", self.debug_id, _cd.COMMIT_AFTER
                )
                span.attribute("Version", commit_id.version)
        self.committed_version = commit_id.version
        self._versionstamp = commit_id.versionstamp
        return commit_id.version

    def reset(self) -> None:
        # the tag survives reset: retried transactions must stay metered
        # (the overload-retry loop is exactly what tag throttling exists
        # to contain)
        self.__init__(self.db, tag=self.tag)


class CommitPipeline:
    """Client-side commit pipelining: keep up to `depth` commits from
    ONE client in flight at once (the reference NativeAPI pattern of
    not awaiting each commit before starting the next — commit latency
    is hidden behind the proxy's batch pipeline instead of serializing
    the client). submit() returns the commit's future immediately and
    only blocks when the window is full; drain() awaits the stragglers.

    Ordering: the proxy pipeline assigns versions in batch order, so
    two pipelined commits may land in the same or successive batches —
    the client must not assume commit N completes before it submits
    commit N+1 (that's the point). Conflict-dependent work (RMW) still
    needs the await before the dependent read.
    """

    def __init__(self, db: "Database", depth: int = 4):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.db = db
        self.depth = depth
        self._inflight: list = []

    async def submit(self, txn: Transaction):
        """Start txn.commit() without awaiting it; returns a future
        (await it for the version / NotCommitted). Blocks only while
        `depth` commits are already outstanding (windowed
        backpressure, oldest-first)."""
        while len(self._inflight) >= self.depth:
            head = self._inflight.pop(0)
            try:
                await head
            except Exception:  # flowcheck: ignore[actor.swallow]
                # not swallowed: the future stays readable and the
                # submitter's handle (the SAME future) carries the error
                pass
        task = self.db.sched.spawn(
            txn.commit(), name=f"commit-pipeline-{id(txn) & 0xFFFF}"
        )
        self._inflight.append(task.done)
        return task.done

    async def drain(self) -> None:
        """Await every outstanding commit (errors surface on the
        futures submit() returned, never here)."""
        inflight, self._inflight = self._inflight, []
        for fut in inflight:
            try:
                await fut
            except Exception:  # flowcheck: ignore[actor.swallow]
                # errors surface on the handles submit() returned (the
                # same multi-awaitable futures) — drain only completes
                pass


def _dedup(ranges):
    return sorted(set(ranges))


class LocationCache:
    """Client-side key -> (range, team) cache with wrong-shard
    invalidation (fdbclient/NativeAPI.actor.cpp:2969-3097
    getCachedKeyLocation / invalidateCache).

    Reads resolve locations from this cache, NOT the authoritative
    keyServers map — the cache may go stale after a shard move; the old
    owner then answers wrong_shard_server, the covering entry is
    invalidated, and the next attempt re-fetches. This is the client
    discipline that makes reads correct once locations travel over a
    wire instead of a shared object (VERDICT r2/r3 carried item)."""

    #: eviction cap — the reference bounds its cache with the
    #: locationCacheSize knob and evicts when full
    #: (fdbclient/NativeAPI.actor.cpp locationCacheSize)
    MAX_ENTRIES = 1024

    def __init__(self, cluster):
        self.cluster = cluster
        # a sorted range map, not a scanned list (the r4 verdict's
        # shape complaint): begins sorted for bisect lookup, entries
        # non-overlapping by construction, FIFO eviction at the cap
        import collections

        self._begins: list[bytes] = []
        self._by_begin: dict[bytes, tuple[bytes, tuple]] = {}
        self._fifo = collections.deque()
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0

    @staticmethod
    def _covers(b: bytes, e: bytes, key: bytes) -> bool:
        return b <= key and (e == b"" or key < e)

    def _index_covering(self, key: bytes) -> int:
        """Index into _begins of the entry covering key, or -1."""
        import bisect

        i = bisect.bisect_right(self._begins, key) - 1
        if i >= 0:
            b = self._begins[i]
            e, _team = self._by_begin[b]
            if self._covers(b, e, key):
                return i
        return -1

    def _remove_at(self, i: int) -> None:
        b = self._begins.pop(i)
        del self._by_begin[b]
        # stale FIFO tokens drain in the eviction loop, but that loop
        # only runs when the cache is over cap — under invalidate/
        # re-locate churn the deque would otherwise grow unboundedly
        # (code review r5): compact when it bloats past 4x the cap
        if len(self._fifo) > 4 * self.MAX_ENTRIES:
            live = set(self._by_begin)
            self._fifo = type(self._fifo)(
                t for t in self._fifo if t in live
            )

    def _insert(self, b: bytes, e: bytes, team: tuple) -> None:
        import bisect

        # drop any overlapping stale entries: [b, e) intersects a
        # contiguous run in begin order
        i = bisect.bisect_right(self._begins, b) - 1
        if i >= 0:
            pe = self._by_begin[self._begins[i]][0]
            if pe == b"" or pe > b:
                self._remove_at(i)
        i = bisect.bisect_left(self._begins, b)
        while i < len(self._begins) and (
            e == b"" or self._begins[i] < e
        ):
            self._remove_at(i)
        bisect.insort(self._begins, b)
        self._by_begin[b] = (e, team)
        self._fifo.append(b)
        while len(self._begins) > self.MAX_ENTRIES and self._fifo:
            victim = self._fifo.popleft()
            if victim == b:
                self._fifo.append(victim)  # never evict the fresh entry
                continue
            if victim in self._by_begin:
                self.evictions += 1
                self._remove_at(self._begins.index(victim))

    def locate(self, key: bytes) -> tuple[bytes, bytes, tuple]:
        """(shard_begin, shard_end, team) for `key`; shard_end == b""
        means the unbounded last shard. Entries hold FULL shard ranges
        (getKeyLocation's contract) — caching a clipped sub-range would
        make range reads crawl it key by key."""
        i = self._index_covering(key)
        if i >= 0:
            self.hits += 1
            b = self._begins[i]
            e, team = self._by_begin[b]
            return b, e, team
        self.misses += 1
        b, e, team = self.cluster.key_servers.range_of(key)
        self._insert(b, e, team)
        return b, e, team

    def invalidate(self, key: bytes) -> None:
        self.invalidations += 1
        i = self._index_covering(key)
        if i >= 0:
            self._remove_at(i)


class Database:
    """Client handle + the run/retry loop (Database::createTransaction)."""

    #: replica/location retry budget per read (loadBalance's bounded
    #: alternatives loop)
    READ_ATTEMPTS = 8

    def __init__(self, cluster):
        from foundationdb_tpu.cluster.queue_model import QueueModel

        self.cluster = cluster
        self.sched = cluster.sched
        self._next_proxy = 0
        self._read_rr = 0  # replica rotation (loadBalance's next-replica)
        self.location_cache = LocationCache(cluster)
        self.dr_locked = False  # set while this db is a DR destination
        # per-replica latency estimates driving read load balancing
        # (fdbrpc/QueueModel.cpp; see cluster/queue_model.py)
        self.queue_model = QueueModel(cluster.sched)
        # TSS read sampling/comparison (cluster/tss.py; design/tss.md)
        from foundationdb_tpu.cluster.tss import TssComparator

        self.tss = TssComparator(cluster.sched, cluster)
        # idempotency-id nonce state: (origin, client, seq) triples are
        # unique across client handles AND client processes without a
        # uuid4 (determinism.unseeded-random): the origin is the sim
        # seed under simulation (replayable) and the OS pid outside it
        self._client_id = cluster.next_client_id()
        self._idemp_seq = 0
        # commit-path tracing (debugTransaction): off by default; the
        # soak trace gate / tools flip it, and every transaction then
        # carries a deterministic (origin, client, seq) debug id
        self.tracing = False
        self._debug_seq = 0

    def next_debug_id(self) -> str:
        """Deterministic transaction debug id (the debugTransaction
        identity): sim-seed origin under simulation, pid outside — same
        discipline as the idempotency nonce, so traced runs replay
        bit-identically."""
        import os

        self._debug_seq += 1
        origin = (
            (self.cluster.config.sim_seed or 0) if self.sched.sim
            else os.getpid()
        )
        return f"{origin}-{self._client_id}-{self._debug_seq}"

    def next_idempotency_id(self) -> bytes:
        """Deterministic idempotency id: 24 bytes of
        (origin, client_id, sequence) — see _client_id above."""
        import os
        import struct

        self._idemp_seq += 1
        if self.sched.sim:
            origin = self.cluster.config.sim_seed or 0
        else:
            # outside simulation, pids recycle: a fresh process handed a
            # predecessor's pid must never replay its id sequence (stale
            # \xff/idmp records would make run(idempotent=True) skip a
            # commit that never happened here — a silently lost write),
            # so fold real entropy under the pid. Sim runs never take
            # this branch, so determinism is untouched.
            origin = (os.getpid() << 32) | int.from_bytes(
                os.urandom(4), "little"  # flowcheck: ignore[determinism.unseeded-random]
            )
        return struct.pack("<qqq", origin, self._client_id, self._idemp_seq)

    @property
    def grv_proxy(self):
        # resolved per call: recovery replaces the GRV proxy generation
        return self.cluster.grv_proxy

    def commit_proxy(self):
        # round-robin over commit proxies (the reference picks randomly)
        p = self.cluster.commit_proxies[
            self._next_proxy % len(self.cluster.commit_proxies)
        ]
        self._next_proxy += 1
        return p

    def _live_rotated(self, team: tuple) -> list:
        """LIVE members of a team, rotated so latency-tied (cold)
        replicas share load round-robin (dead replicas are skipped —
        the failure-monitor contract)."""
        live = [s for s in team if self.cluster.storage_live[s]]
        if not live:
            live = list(team)  # nothing marked live: fall back, will hang
        self._read_rr += 1
        k = self._read_rr % len(live)
        return live[k:] + live[:k]

    def _pick_replica(self, team: tuple) -> int:
        """Best replica by the QueueModel latency estimate
        (fdbrpc/LoadBalance.actor.h replica selection)."""
        return self.queue_model.order(self._live_rotated(team))[0]

    def storage_for(self, key: bytes):
        _b, _e, team = self.location_cache.locate(key)
        return self.cluster.client_storages[self._pick_replica(team)]

    def _report_failed(self, s: int) -> None:
        fm = getattr(self.cluster, "failure_monitor", None)
        if fm is not None:
            fm.report_failed(f"storage{s}")
        else:
            self.cluster.storage_live[s] = False

    async def read_value(self, key: bytes, rv: int):
        """Point read through the location cache with the reference's
        two error-recovery loops: wrong_shard_server -> invalidate +
        re-resolve; process failure -> report to the failure monitor +
        fail over to another replica."""
        from foundationdb_tpu.cluster.failure_monitor import ProcessFailedError
        from foundationdb_tpu.cluster.storage import (
            TransactionTooOld,
            WrongShardServerError,
        )

        from foundationdb_tpu.cluster.queue_model import load_balanced_call

        def issue(s):
            async def go():
                try:
                    return await self.cluster.client_storages[s].get_value(
                        key, rv
                    )
                except ProcessFailedError:
                    # report at the issuing site: the balancer only sees
                    # "some replica failed", the monitor needs WHICH
                    self._report_failed(s)
                    raise
            return go()

        err = None
        for _ in range(self.READ_ATTEMPTS):
            _b, _e, team = self.location_cache.locate(key)
            try:
                result = await load_balanced_call(
                    self.sched, self.queue_model,
                    self._live_rotated(team), issue,
                )
                # TSS sampling: replicas hold identical content at rv,
                # so any TSS-paired team member's mirror is a valid
                # comparison target; fire-and-forget, off the hot path
                for s in team:
                    if s in getattr(self.cluster, "client_tss", {}):
                        self.tss.maybe_sample(s, key, rv, result)
                        break
                return result
            except WrongShardServerError as e:
                err = e
                self.location_cache.invalidate(key)
            except ProcessFailedError as e:
                err = e
            except TransactionTooOld:
                # the storage GC'd past our read version: surface the
                # CLIENT-level retryable error (error_code_transaction_
                # too_old reaches Transaction::onError in the reference)
                raise TransactionTooOldError(
                    f"read at {rv} below the storage MVCC window"
                )
        raise err

    async def read_range(self, begin: bytes, end: bytes, rv: int):
        """Range read segment-by-segment through the location cache,
        with the same wrong-shard/failure recovery per segment."""
        from foundationdb_tpu.cluster.failure_monitor import ProcessFailedError
        from foundationdb_tpu.cluster.storage import (
            TransactionTooOld,
            WrongShardServerError,
        )

        items: list[tuple[bytes, bytes]] = []
        cursor = begin
        attempts = 0
        while cursor < end:
            _b, seg_e, team = self.location_cache.locate(cursor)
            seg_end = end if seg_e == b"" else min(seg_e, end)
            s = self._pick_replica(team)
            t0 = self.queue_model.start(s)
            ok = False
            try:
                items.extend(
                    await self.cluster.client_storages[s].get_key_values(
                        cursor, seg_end, rv
                    )
                )
                ok = True
            except WrongShardServerError:
                self.location_cache.invalidate(cursor)
                attempts += 1
                if attempts > self.READ_ATTEMPTS:
                    raise
                continue
            except ProcessFailedError:
                self._report_failed(s)
                attempts += 1
                if attempts > self.READ_ATTEMPTS:
                    raise
                continue
            except TransactionTooOld:
                raise TransactionTooOldError(
                    f"read at {rv} below the storage MVCC window"
                )
            finally:
                # finally, not per-handler: an unexpected error (or the
                # task being cancelled at the await) must not leak the
                # outstanding increment and bias reads off this replica
                self.queue_model.finish(s, t0, failed=not ok)
            cursor = seg_end
            # budget retries per segment, not per scan: a long range
            # crossing many concurrently-moving shards must not exhaust
            # the budget when each individual segment retry would have
            # succeeded (ADVICE r4; NativeAPI retries per getRange leg)
            attempts = 0
        return items

    def create_transaction(self, tag: str = None) -> Transaction:
        return Transaction(self, tag=tag)

    def commit_pipeline(self, depth: int = 4) -> CommitPipeline:
        """Client-side commit pipelining (see CommitPipeline): up to
        `depth` commits from this client in flight concurrently."""
        return CommitPipeline(self, depth=depth)

    def special_key(self, key: bytes):
        """The \\xff\\xff special key space (SpecialKeySpace.actor.cpp):
        virtual reads of management/status information."""
        import json

        if key == b"\xff\xff/status/json":
            from foundationdb_tpu.cluster.status import cluster_status

            return json.dumps(cluster_status(self.cluster)).encode()
        if key == b"\xff\xff/cluster/epoch":
            return str(self.cluster.controller.epoch).encode()
        if key == b"\xff\xff/cluster/live_committed_version":
            return str(self.cluster.sequencer.live_committed.get()).encode()
        if key == b"\xff\xff/worker_interfaces":
            # the recruited role inventory (worker_interfaces module of
            # SpecialKeySpace: who is serving what)
            return json.dumps({
                "commit_proxies": [p.proxy_id for p in
                                   self.cluster.commit_proxies],
                "resolvers": [f"resolver{r.resolver_id}"
                              for r in self.cluster.resolvers],
                "storage": [f"storage{i}" for i, live in
                            enumerate(self.cluster.storage_live) if live],
                "coordinators": [c.name for c in self.cluster.coordinators
                                 if c.alive],
            }).encode()
        if key == b"\xff\xff/metrics/resolver":
            # resolver counter rollup (the metrics module surface)
            out = []
            for r in self.cluster.resolvers:
                out.append(r.counters.as_dict())
            return json.dumps(out).encode()
        if key == b"\xff\xff/coordinators":
            return json.dumps({
                "quorum": len(self.cluster.coordinators) // 2 + 1,
                "alive": sum(c.alive for c in self.cluster.coordinators),
                "total": len(self.cluster.coordinators),
            }).encode()
        if key == b"\xff\xff/data_distribution/key_counts":
            return json.dumps(
                self.cluster.data_distributor.key_counts()).encode()
        return None

    async def run(self, fn, *, max_retries: int = 50, idempotent: bool = False):
        """retry_loop(fn): the standard transaction retry pattern
        (Transaction::onError — not_committed and too-old retry with a
        fresh read version). With idempotent=True, commit_unknown_result
        retries first check the idempotency record so a commit that DID
        apply is not applied twice."""
        backoff = 0.001
        idemp_id = None
        for _ in range(max_retries):
            txn = self.create_transaction()
            if idempotent:
                idemp_id = txn.set_idempotency_id(idemp_id)
            try:
                result = await fn(txn)
                await txn.commit()
                return result
            except CommitUnknownResult:
                if idemp_id is not None:
                    probe = self.create_transaction()
                    try:
                        mark = await probe.get(
                            b"\xff/idmp/" + idemp_id, snapshot=True
                        )
                    except (TransactionTooOldError, GrvProxyFailedError,
                            GrvThrottledError):
                        mark = None
                    if mark is not None:
                        return result  # the first attempt committed
                await self.sched.delay(backoff)
                backoff = min(backoff * 2, 0.1)
            except (NotCommitted, TransactionTooOldError,
                    GrvProxyFailedError, GrvThrottledError):
                # grv_throttled: the front door shed this request under
                # overload — the exponential backoff below IS the
                # client side of the admission-control contract
                await self.sched.delay(backoff)
                backoff = min(backoff * 2, 0.1)
        raise RuntimeError("transaction retry limit reached")
