"""MultiVersion client: protocol negotiation + hot-swap on upgrade.

Capability match for fdbclient/MultiVersionTransaction.actor.cpp + the
multi-version layer of bindings/c/fdb_c.cpp: a client process that may
outlive a cluster upgrade carries SEVERAL client implementations (in
the reference: dynamically loaded libfdb_c versions; here: per-protocol
connection factories), probes which one the cluster speaks, and when
the cluster's protocol CHANGES (upgrade restart), fails outstanding
work with cluster_version_changed — the retryable error the reference
surfaces so transaction loops restart on the freshly selected client —
and reconnects through the newly matching implementation.

The probe mirrors the reference's protocol-version watch
(getClusterProtocol): try the most recent known version first, walk
down on handshake rejection.
"""

from __future__ import annotations

from typing import Callable

from foundationdb_tpu.utils.trace import SEV_WARN, TraceEvent
from foundationdb_tpu.wire import transport


class ClusterVersionChangedError(RuntimeError):
    """error_code_cluster_version_changed: the cluster now speaks a
    different protocol; the operation must retry on the re-selected
    client (MultiVersionTransaction's cluster_version_changed)."""


class MultiVersionClient:
    """Manage one logical connection across protocol versions.

    `versions`: newest-first protocol versions this client ships
    support for. `factory(address, protocol_version)` builds an
    RpcConnection-compatible object (default: the wire transport)."""

    def __init__(self, address, versions: list[int], *,
                 factory: Callable = None, tls=None):
        if not versions:
            raise ValueError("at least one protocol version required")
        self.address = address
        self.versions = list(versions)
        self.tls = tls
        self._factory = factory or (
            lambda addr, pv: transport.RpcConnection(
                addr, tls=tls, protocol_version=pv
            )
        )
        self.conn = None
        self.protocol_version: int | None = None
        self.swaps = 0  # upgrades survived (observability/tests)
        self._connect_lock = None  # single-flight connect (lazy: needs loop)

    async def connect(self, *, retries: int = 20, delay: float = 0.05):
        """Probe supported versions newest-first until one handshakes —
        the reference's protocol discovery. SINGLE-FLIGHT: concurrent
        failed calls reconnect once, not once each (a racing pair would
        overwrite and leak a live connection — third review pass).
        Returns the connection."""
        import asyncio

        if self._connect_lock is None:
            self._connect_lock = asyncio.Lock()
        async with self._connect_lock:
            if self.conn is not None:
                return self.conn
            return await self._connect_locked(retries, delay)

    async def _connect_locked(self, retries: int, delay: float):
        last = None
        for _ in range(retries):
            for pv in self.versions:
                conn = self._factory(self.address, pv)
                try:
                    await conn.connect(retries=1, delay=delay)
                    if (
                        self.protocol_version is not None
                        and pv != self.protocol_version
                    ):
                        self.swaps += 1
                        TraceEvent(
                            "MultiVersionClientSwapped", severity=SEV_WARN
                        ).detail("From", self.protocol_version).detail(
                            "To", pv
                        ).log()
                    self.conn = conn
                    self.protocol_version = pv
                    return conn
                except transport.TransportError as e:
                    last = e
                    await conn.close()
            import asyncio

            await asyncio.sleep(delay)
        raise transport.TransportError(
            f"no supported protocol version accepted by {self.address} "
            f"(tried {[hex(v) for v in self.versions]}): {last}"
        )

    async def call(self, token: int, msg, *, timeout: float = 30.0):
        """One RPC, AT-MOST-ONCE: a connection loss reconnects (probing
        versions) and then RAISES — ClusterVersionChangedError when the
        cluster moved protocols, TransportError otherwise — rather than
        silently re-sending a request the server may already have
        executed (non-idempotent double-apply; code review r5). The
        retry decision belongs to the caller's transaction loop, as in
        the reference (MultiVersionTransaction surfaces retryable
        errors to onError)."""
        if self.conn is None:
            await self.connect()
        conn = self.conn
        try:
            return await conn.call(token, msg, timeout=timeout)
        except (transport.TransportError, ConnectionError) as e:
            old_pv = self.protocol_version
            # concurrent calls share the connection and fail together;
            # tear down only the conn THIS call used — by identity, so
            # a second handler never closes the freshly rebuilt one
            # (second review pass)
            if self.conn is conn:
                self.conn = None
                await conn.close()
                await self.connect()  # next call rides the fresh client
            if self.protocol_version != old_pv:
                raise ClusterVersionChangedError(
                    f"cluster protocol moved {old_pv:#x} -> "
                    f"{self.protocol_version:#x}; retry on the new client"
                ) from e
            raise transport.TransportError(
                f"connection to {self.address} lost mid-call; the "
                "request may or may not have executed — caller retries"
            ) from e

    async def close(self):
        if self.conn is not None:
            await self.conn.close()
            self.conn = None
