"""Blob granules: key ranges materialized as snapshot + delta files.

Behavioral mirror of the reference's largest subsystem absent until now
(fdbserver/BlobManager.actor.cpp, fdbserver/BlobWorker.actor.cpp,
fdbclient/BlobGranuleFiles.cpp): the keyspace is carved into GRANULES;
a BlobWorker tails the log system and materializes each granule as a
base SNAPSHOT file plus ordered DELTA files in a blob container, so a
reader can reconstruct the granule's contents at any version in the
retention window WITHOUT touching the storage servers — cheap analytics
scans and time travel off the hot path.

Shape notes vs the reference:
* Files live in the existing BackupContainer abstraction (memory or
  dir) — the reference's S3/azure containers are a transport detail.
* The worker consumes the tlog's full-stream tag exactly like the
  backup agent (one copy of each mutation, commit order), routes
  mutations to granules by key, and flushes a granule's delta buffer
  once it crosses DELTA_FLUSH_BYTES (BlobWorker.actor.cpp's
  writeDeltaFile trigger).
* Re-snapshotting: once a granule's accumulated delta bytes pass
  SNAPSHOT_AT_DELTA_BYTES, the worker folds snapshot+deltas into a new
  snapshot file at the flush version (granule compaction,
  BlobWorker.actor.cpp:compactBlobGranule); older files stay for time
  travel until pruned.
* The BlobManager owns the granule map, persists it under
  `\\xff/blobGranuleMapping/`, and SPLITS a granule whose materialized
  size crosses SPLIT_BYTES (BlobManager.actor.cpp's
  maybeSplitRange) — split points come from the granule's own sorted
  keys, so halves are balanced by bytes, not keyspace.

File naming (sortable, version-zero-padded like the backup layout):
  granules/<gid>/snapshot/<v16>      json {key_hex: value_hex}
  granules/<gid>/delta/<v16>         json [[v, [mutation...]], ...]
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.probes import code_probe, declare

declare(
    "blob.delta_flushed",
    "blob.resnapshotted",
    "blob.granule_split",
    "blob.time_travel_read",
)

MAPPING_PREFIX = b"\xff/blobGranuleMapping/"


def _hex(b: bytes) -> str:
    return b.hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s)


@dataclasses.dataclass
class Granule:
    gid: int
    begin: bytes
    end: bytes  # b"" = unbounded
    #: in-memory tail: mutations at versions newer than the last flush
    buffer: list  # [(version, mutation)]
    buffer_bytes: int = 0
    #: bytes of delta files since the last snapshot (re-snapshot trigger)
    delta_bytes_since_snapshot: int = 0
    last_flush_version: int = 0
    #: materialized bytes of the last snapshot file (cheap size estimate)
    snapshot_bytes: int = 0
    #: (version, gid) file refs — gid names the DIRECTORY holding the
    #: file, which is an ANCESTOR's for refs inherited across a split
    #: (time travel below the split version reads the parent's files)
    snapshot_versions: list = dataclasses.field(default_factory=list)
    delta_versions: list = dataclasses.field(default_factory=list)

    def covers(self, key: bytes) -> bool:
        return self.begin <= key and (self.end == b"" or key < self.end)


class BlobWorker:
    """Materializes assigned granules from the log stream
    (fdbserver/BlobWorker.actor.cpp)."""

    DELTA_FLUSH_BYTES = 4 << 10
    SNAPSHOT_AT_DELTA_BYTES = 16 << 10

    def __init__(self, sched: Scheduler, tlog, container, *,
                 name: str = "blobworker0"):
        from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG

        self.sched = sched
        self.tlog = tlog
        self.container = container
        self.name = name
        self.granules: dict[int, Granule] = {}
        self.version = 0  # granule data complete through this version
        self._tag = LOG_STREAM_TAG
        self._task = None
        self.manager: Optional["BlobManager"] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if hasattr(self.tlog, "register_consumer"):
            self.tlog.register_consumer(self.name)
        self._task = self.sched.spawn(self._pull(), name=self.name)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if hasattr(self.tlog, "unregister_consumer"):
            # a stopped worker must not pin the full log stream: its pop
            # mark would freeze the tlog's trim floor forever
            self.tlog.unregister_consumer(self.name)

    def assign(self, g: Granule) -> None:
        self.granules[g.gid] = g

    def unassign(self, gid: int) -> "Granule | None":
        return self.granules.pop(gid, None)

    # -- the log tail ----------------------------------------------------

    async def _pull(self) -> None:
        after = self.version
        while True:
            got, log_version = await self.tlog.peek(self._tag, after)
            for v, msgs in got:
                for m in msgs:
                    self._route(v, m)
            after = max(log_version, max((v for v, _ in got), default=0))
            self.version = after
            # snapshot the dict: a flush can trigger a manager split
            # that assigns the new child granule to this worker
            for g in list(self.granules.values()):
                if g.buffer_bytes >= self.DELTA_FLUSH_BYTES:
                    self._flush_delta(g)
            self.tlog.pop(self._tag, after, consumer=self.name)
            await self.tlog.version.when_at_least(after + 1)

    def _route(self, v: int, m) -> None:
        if m[0] == "set":
            for g in self.granules.values():
                if g.covers(m[1]):
                    g.buffer.append((v, m))
                    g.buffer_bytes += len(m[1]) + len(m[2]) + 16
                    break
        else:  # clear range: may straddle granules; clip per granule
            # (no unbounded-clear convention exists in the mutation
            # stream: clear ends are always concrete keys)
            _, cb, ce = m
            for g in self.granules.values():
                lo = max(cb, g.begin)
                hi = ce if g.end == b"" else min(ce, g.end)
                if lo < hi:
                    g.buffer.append((v, ("clear", lo, hi)))
                    g.buffer_bytes += len(lo) + len(hi) + 16

    # -- files -----------------------------------------------------------

    def _flush_delta(self, g: Granule) -> None:
        if not g.buffer:
            return
        v = max(ver for ver, _ in g.buffer)
        payload = [
            [ver, [mut[0]] + [_hex(x) for x in mut[1:]]]
            for ver, mut in g.buffer
        ]
        self.container.write_file(
            f"granules/{g.gid}/delta/{v:016d}", payload
        )
        code_probe(True, "blob.delta_flushed")
        g.delta_versions.append((v, g.gid))
        g.delta_bytes_since_snapshot += g.buffer_bytes
        g.buffer = []
        g.buffer_bytes = 0
        g.last_flush_version = v
        if g.delta_bytes_since_snapshot >= self.SNAPSHOT_AT_DELTA_BYTES:
            self._resnapshot(g, v)
        if self.manager is not None:
            self.manager.note_granule_size(g)

    def _resnapshot(self, g: Granule, v: int) -> None:
        """Fold snapshot+deltas into a fresh snapshot at v (granule
        compaction). Old files remain for time travel."""
        kvs = self.materialize(g, v)
        self.container.write_file(
            f"granules/{g.gid}/snapshot/{v:016d}",
            {_hex(k): _hex(val) for k, val in kvs.items()},
        )
        code_probe(True, "blob.resnapshotted")
        g.snapshot_versions.append((v, g.gid))
        g.snapshot_bytes = sum(len(k) + len(x) for k, x in kvs.items())
        g.delta_bytes_since_snapshot = 0

    def snapshot_granule(self, g: Granule, kvs: dict, v: int) -> None:
        """Initial materialization from a storage snapshot (the
        BlobWorker's opening snapshot when a granule is first assigned)."""
        self.container.write_file(
            f"granules/{g.gid}/snapshot/{v:016d}",
            {_hex(k): _hex(val) for k, val in kvs.items()},
        )
        g.snapshot_versions.append((v, g.gid))
        g.snapshot_bytes = sum(len(k) + len(x) for k, x in kvs.items())
        g.last_flush_version = max(g.last_flush_version, v)

    def force_flush(self, version: int) -> None:
        """Flush every granule's buffer so files cover `version` (the
        read path's flush-before-read, BlobWorker readBlobGranule)."""
        # list(): a flush can trigger a split that assigns a new child
        for g in list(self.granules.values()):
            if g.buffer and g.last_flush_version < version:
                self._flush_delta(g)

    # -- reads -----------------------------------------------------------

    def materialize(self, g: Granule, version: int) -> dict[bytes, bytes]:
        """Granule contents at `version` from FILES + the memory tail
        (fdbclient/BlobGranuleFiles.cpp materializeBlobGranule)."""
        base = {}
        snaps = [(sv, gid) for sv, gid in g.snapshot_versions
                 if sv <= version]
        snap_v, snap_gid = max(snaps) if snaps else (0, g.gid)
        if snaps:
            raw = self.container.read_file(
                f"granules/{snap_gid}/snapshot/{snap_v:016d}"
            )
            base = {_unhex(k): _unhex(val) for k, val in raw.items()}
        for dv, dgid in sorted(g.delta_versions):
            if dv <= snap_v:
                continue  # folded into the snapshot already
            raw = self.container.read_file(f"granules/{dgid}/delta/{dv:016d}")
            for ver, mut in raw:
                if snap_v < ver <= version:
                    self._apply(base, mut[0], *(_unhex(x) for x in mut[1:]))
        for ver, mut in g.buffer:
            if snap_v < ver <= version:
                self._apply(base, mut[0], *mut[1:])
        # clip to the granule's CURRENT range: after a split the parent's
        # older files still span the pre-split range, and those foreign
        # keys now belong to (and may be stale vs) the sibling granule
        return {k: v for k, v in base.items() if g.covers(k)}

    @staticmethod
    def _apply(base: dict, op: str, *args) -> None:
        if op == "set":
            base[args[0]] = args[1]
        else:
            b, e = args
            for k in [k for k in base if k >= b and (e == b"" or k < e)]:
                del base[k]


class BlobManager:
    """Owns the granule map: assignment, persistence, splitting
    (fdbserver/BlobManager.actor.cpp)."""

    SPLIT_BYTES = 48 << 10

    def __init__(self, db, workers: list[BlobWorker]):
        self.db = db
        self.workers = workers
        self.granules: dict[int, Granule] = {}
        self.assignment: dict[int, BlobWorker] = {}
        self._next_gid = 0
        for w in workers:
            w.manager = self

    # -- range management ------------------------------------------------

    async def blobbify(self, begin: bytes, end: bytes,
                       snapshot: dict, version: int) -> Granule:
        """Start materializing [begin, end): create the granule, write
        its opening snapshot, persist the mapping. Clamped to the NORMAL
        keyspace — the system keyspace is never blobbified (the
        reference's blobbifiable range check, BlobManager.actor.cpp:
        isRangeValid), not least because the granule mapping itself
        lives there."""
        if end == b"" or end > b"\xff":
            end = b"\xff"
        for other in self.granules.values():
            if begin < other.end and other.begin < end:
                raise ValueError(
                    f"range overlaps granule {other.gid} "
                    f"[{other.begin!r}, {other.end!r})"
                )
        g = Granule(self._next_gid, begin, end, [])
        self._next_gid += 1
        self.granules[g.gid] = g
        w = self.workers[g.gid % len(self.workers)]
        w.assign(g)
        self.assignment[g.gid] = w
        w.snapshot_granule(
            g,
            {k: v for k, v in snapshot.items() if g.covers(k)},
            version,
        )
        await self._persist_mapping()
        return g

    async def _persist_mapping(self) -> None:
        txn = self.db.create_transaction()
        txn.clear_range(MAPPING_PREFIX, MAPPING_PREFIX + b"\xff")
        for g in self.granules.values():
            txn.set(
                MAPPING_PREFIX + b"%08d" % g.gid,
                repr((g.begin, g.end, self.assignment[g.gid].name)).encode(),
            )
        await txn.commit()

    async def _persist_mapping_bg(self) -> None:
        """Background persist for the post-split path: a mapping write
        racing data-plane chaos must not become an escaped actor error —
        the in-memory mapping is authoritative and the next persist
        rewrites the full keyspace anyway."""
        try:
            await self._persist_mapping()
        except ActorCancelled:
            raise
        except Exception as e:
            from foundationdb_tpu.utils.trace import SEV_WARN, TraceEvent

            TraceEvent("BlobMappingPersistFailed", severity=SEV_WARN) \
                .detail("Err", repr(e)).log()

    def note_granule_size(self, g: Granule) -> None:
        """Worker size report: split when materialized size crosses
        SPLIT_BYTES (BlobManager maybeSplitRange). Split is local and
        synchronous; the mapping re-persists asynchronously."""
        w = self.assignment.get(g.gid)
        if w is None:
            return
        # cheap estimate FIRST (snapshot + deltas since): the full
        # materialize below is O(granule) and must not run per 4KB flush
        if g.snapshot_bytes + g.delta_bytes_since_snapshot < self.SPLIT_BYTES:
            return
        kvs = w.materialize(g, w.version)
        size = sum(len(k) + len(v) for k, v in kvs.items())
        if size < self.SPLIT_BYTES or len(kvs) < 2:
            return
        keys = sorted(kvs)
        # byte-balanced split point from the granule's own keys
        acc, half = 0, size // 2
        split = keys[len(keys) // 2]
        for k in keys:
            acc += len(k) + len(kvs[k])
            if acc >= half:
                split = k
                break
        if split <= g.begin or (g.end != b"" and split >= g.end):
            return
        code_probe(True, "blob.granule_split")
        right = Granule(self._next_gid, split, g.end, [])
        self._next_gid += 1
        v = w.version
        # buffered mutations are all <= w.version and therefore folded
        # into the children's opening snapshots below: buffers restart
        # empty on both sides
        g.end, g.buffer, g.buffer_bytes = split, [], 0
        # the right child INHERITS the parent's file refs: time travel
        # below the split version reads the parent's files (clipped to
        # the child's range by materialize)
        right.snapshot_versions = list(g.snapshot_versions)
        right.delta_versions = list(g.delta_versions)
        self.granules[right.gid] = right
        w.assign(right)
        self.assignment[right.gid] = w
        w.snapshot_granule(
            g, {k: val for k, val in kvs.items() if k < split}, v)
        w.snapshot_granule(
            right, {k: val for k, val in kvs.items() if k >= split}, v)
        g.delta_bytes_since_snapshot = 0
        # fire-and-forget by design (the split already happened; the next
        # assign/split re-persists the full mapping) — _persist_mapping_bg
        # contains its own errors so chaos can't crash the manager
        self.db.sched.spawn(self._persist_mapping_bg(), name="blob-mapping")  # flowcheck: ignore[actor.fire-and-forget]

    # -- reads -----------------------------------------------------------

    def read(self, begin: bytes, end: bytes,
             version: Optional[int] = None) -> dict[bytes, bytes]:
        """Point-in-time read of [begin, end) from granule files alone
        (readBlobGranules). None = newest materialized version."""
        out = {}
        if version is None:
            # one version for the WHOLE read: per-worker versions would
            # tear a cross-granule transaction when granules live on
            # different workers
            workers = {self.assignment[g.gid] for g in self.granules.values()}
            version_eff = min((w.version for w in workers), default=0)
        else:
            version_eff = version
        code_probe(version is not None, "blob.time_travel_read")
        # flush FIRST, then snapshot the granule list: a flush-triggered
        # split narrows a parent and creates a child, and a list taken
        # before the flush would miss the child's half of the keyspace.
        # Only workers owning RANGE-OVERLAPPING granules flush (children
        # stay on the parent's worker), and dict.fromkeys keeps the
        # iteration order deterministic — a set of objects would flush
        # in id() order and let split gid allocation diverge between
        # same-seed runs
        overlapping = [
            self.assignment[g.gid]
            for g in list(self.granules.values())
            if not (g.end != b"" and g.end <= begin)
            and not (end != b"" and g.begin >= end)
        ]
        for w in dict.fromkeys(overlapping):
            w.force_flush(version_eff)
        for g in list(self.granules.values()):
            if g.end != b"" and g.end <= begin:
                continue
            if end != b"" and g.begin >= end:
                continue
            w = self.assignment[g.gid]
            for k, val in w.materialize(g, version_eff).items():
                if k >= begin and (end == b"" or k < end):
                    out[k] = val
        return out
