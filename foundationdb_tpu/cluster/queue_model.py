"""Per-replica latency model + backup-request load balancing.

Behavioral mirror of fdbrpc/QueueModel.cpp + LoadBalance.actor.h: the
client keeps an EWMA latency estimate and an outstanding-request count
per storage endpoint; reads go to the replica with the smallest expected
latency, and a BACKUP request is armed on the next-best replica when the
primary hasn't answered within a multiple of its expected latency —
first reply wins, and the duplicated loser runs to completion so its
eventual latency is still observed. A slow-but-alive replica
therefore stops receiving the bulk of reads without any failure-monitor
involvement (it is throttled by its own measured latency), while a
recovered replica is re-probed after its estimate goes stale.

The reference's TSS mirror-pairing rides the same machinery
(fdbrpc/LoadBalance.actor.h loadBalance); not implemented here.
"""

from __future__ import annotations

import dataclasses

from foundationdb_tpu.utils.probes import code_probe, declare

declare(
    "loadbalance.backup_request",
    "loadbalance.backup_won",
    "loadbalance.slow_replica_shunned",
)


@dataclasses.dataclass
class _EndpointStats:
    latency: float      # EWMA seconds
    outstanding: int
    last_update: float  # sched time of the last observation


class QueueModel:
    """Latency estimates per endpoint (fdbrpc/QueueModel.cpp).

    expected() = EWMA latency x (1 + outstanding): queued requests
    inflate the estimate exactly like the reference's penalty so a
    pile-up on one replica sheds to its peers before replies even come
    back. An UNTRIED endpoint estimates 0 — unknown servers are probed
    first, the reference's loadBalance discipline (otherwise a single
    fast reply would lock in the first-tried replica forever). Estimates
    older than STALE_AFTER decay back to the untried prior so a
    recovered replica gets re-probed.
    """

    ALPHA = 0.25          # EWMA weight of a new observation
    PRIOR = 0.0           # untried endpoints are assumed fast: probe them
    STALE_AFTER = 2.0     # seconds without data -> treat as cold again
    #: absolute per-outstanding-request charge: an endpoint with an
    #: unanswered request in flight must lose ties against idle peers
    #: even while its EWMA is still zero/cold (QueueModel.cpp's queue
    #: penalty is likewise additive)
    QUEUE_PENALTY = 0.001

    def __init__(self, sched):
        self.sched = sched
        self._stats: dict[object, _EndpointStats] = {}

    def expected(self, ep) -> float:
        st = self._stats.get(ep)
        if st is None:
            return self.PRIOR
        if self.sched.now() - st.last_update > self.STALE_AFTER:
            # stale: decay PERSISTENTLY to the untried prior — the next
            # observation must re-seed the EWMA from cold, not from the
            # old (possibly slow-era) value, or one successful re-probe
            # would immediately re-shun a recovered replica
            st.latency = min(st.latency, self.PRIOR)
        return (
            st.latency * (1 + st.outstanding)
            + st.outstanding * self.QUEUE_PENALTY
        )

    def order(self, endpoints) -> list:
        """Endpoints sorted by expected latency. The sort is STABLE and
        the key is the estimate alone, so the caller's rotation of the
        candidate list spreads ties (cold replicas) round-robin."""
        return sorted(endpoints, key=self.expected)

    def start(self, ep) -> float:
        st = self._stats.get(ep)
        if st is None:
            st = self._stats[ep] = _EndpointStats(
                self.PRIOR, 0, self.sched.now()
            )
        st.outstanding += 1
        return self.sched.now()

    def finish(self, ep, t0: float, failed: bool = False) -> None:
        st = self._stats.get(ep)
        if st is None:
            return
        st.outstanding = max(0, st.outstanding - 1)
        obs = self.sched.now() - t0
        if failed:
            # a failed request says nothing about queue latency; keep the
            # estimate but stamp the time so it does not instantly decay
            st.last_update = self.sched.now()
            return
        st.latency = (1 - self.ALPHA) * st.latency + self.ALPHA * obs
        st.last_update = self.sched.now()


#: arm the backup request at this multiple of the primary's expected
#: latency (LoadBalance.actor.h's backup delay discipline)
BACKUP_DELAY_MULT = 4.0
BACKUP_DELAY_MIN = 0.002


async def load_balanced_call(sched, model: QueueModel, replicas: list,
                             issue):
    """One logical request over ordered replicas with a backup request.

    `replicas`: candidate endpoints (already filtered for liveness).
    `issue(ep)`: coroutine factory performing the request against ep.
    Returns the first successful reply. If the primary is slower than
    BACKUP_DELAY_MULT x its expected latency, the request is DUPLICATED
    to the next replica and the first reply wins (the reference's
    backup-request discipline — duplication, not failover, so a stalled
    primary costs nothing extra when it eventually answers). The losing
    request is NOT cancelled: it runs to completion so its eventual
    latency lands in the model (that observation is what marks a
    stalled replica slow). Errors surface from whichever request fails
    last-standing.
    """
    from foundationdb_tpu.runtime.flow import ActorCancelled, any_of

    order = model.order(replicas)
    primary = order[0]
    # absolute floor: with a cold primary (expected 0) any nonzero
    # peer estimate would otherwise read as a "shun"
    code_probe(
        len(order) > 1
        and model.expected(order[-1])
        > max(10 * model.expected(primary), 0.005),
        "loadbalance.slow_replica_shunned",
    )
    # expected() BEFORE start(): the request's own outstanding penalty
    # must not inflate its backup delay
    primary_expected = model.expected(primary)
    t0 = model.start(primary)
    pt = sched.spawn(issue(primary), name="lb-primary")
    if len(order) == 1:
        try:
            r = await pt.done
            model.finish(primary, t0)
            return r
        except BaseException:
            model.finish(primary, t0, failed=True)
            raise

    backup_after = max(
        BACKUP_DELAY_MULT * primary_expected, BACKUP_DELAY_MIN
    )
    try:
        await any_of([pt.done, sched.delay(backup_after)])
    except ActorCancelled:
        model.finish(primary, t0, failed=True)
        raise  # cancellation must not leak the outstanding increment
    # a primary error is handled by inspecting pt.done below, where the
    # failure updates the model before re-raising — nothing is dropped
    except BaseException:  # flowcheck: ignore[actor.swallow]
        pass
    if pt.done.is_ready:
        try:
            r = pt.done.get()
            model.finish(primary, t0)
            return r
        except BaseException:
            model.finish(primary, t0, failed=True)
            raise

    # primary is slow: duplicate to the next-best replica
    code_probe(True, "loadbalance.backup_request")
    secondary = order[1]
    t1 = model.start(secondary)
    bt = sched.spawn(issue(secondary), name="lb-backup")
    try:
        await any_of([pt.done, bt.done])
    except ActorCancelled:
        model.finish(primary, t0, failed=True)
        model.finish(secondary, t1, failed=True)
        raise
    # per-request errors are handled below (first/other inspection):
    # both futures' outcomes are consumed either way
    except BaseException:  # flowcheck: ignore[actor.swallow]
        pass
    first, other = (pt, bt) if pt.done.is_ready else (bt, pt)
    f_ep, f_t0, o_ep, o_t0 = (
        (primary, t0, secondary, t1)
        if first is pt
        else (secondary, t1, primary, t0)
    )
    try:
        r = first.done.get()
        model.finish(f_ep, f_t0)
        code_probe(first is bt, "loadbalance.backup_won")
        # the duplicated request keeps running (reads are idempotent);
        # record its EVENTUAL latency — that observation is exactly what
        # marks a stalled-but-alive replica slow and sheds future load
        _observe_when_done(model, o_ep, o_t0, other)
        return r
    except BaseException:
        model.finish(f_ep, f_t0, failed=True)
        # first responder failed: the other request is still in flight
        try:
            r = await other.done
            model.finish(o_ep, o_t0)
            return r
        except BaseException:
            model.finish(o_ep, o_t0, failed=True)
            raise


def _observe_when_done(model: QueueModel, ep, t0: float, task) -> None:
    def cb(fut):
        try:
            fut.get()
        except BaseException:
            model.finish(ep, t0, failed=True)
        else:
            model.finish(ep, t0)

    task.done.add_done_callback(cb)
