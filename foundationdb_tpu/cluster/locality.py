"""Locality + replication policies: teams spread across failure domains.

The roles of `fdbrpc/Locality.cpp` (LocalityData: processid / machineid /
zoneid / dcid) and `fdbrpc/ReplicationPolicy.cpp` (IReplicationPolicy —
`PolicyOne`, `PolicyAcross(n, field, inner)`): recruitment and team
building must place replicas across distinct failure domains ("three
replicas across three zoneids"), and validation answers whether a given
team satisfies the policy.

`build_team` is the greedy selector DDTeamCollection uses in spirit:
prefer servers whose addition keeps the policy satisfiable, fail loudly
when the topology cannot satisfy it.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional


@dataclasses.dataclass(frozen=True)
class LocalityData:
    """fdbrpc LocalityData: the standard failure-domain keys."""

    process_id: str
    machine_id: Optional[str] = None
    zone_id: Optional[str] = None
    dc_id: Optional[str] = None

    def get(self, field: str) -> Optional[str]:
        return getattr(self, field)


class PolicyOne:
    """Any single replica satisfies the policy (replication factor 1)."""

    name = "One"

    def validate(self, team: list[LocalityData]) -> bool:
        return len(team) >= 1

    @property
    def min_replicas(self) -> int:
        return 1

    def __repr__(self):
        return "PolicyOne()"


class PolicyAcross:
    """`Across(n, field, inner)`: n groups with DISTINCT values of
    `field`, each group satisfying `inner` (ReplicationPolicy.cpp's
    recursive composition — e.g. Across(2, 'dc_id', Across(2, 'zone_id',
    One())) = two DCs, two zones in each)."""

    def __init__(self, count: int, field: str, inner=None):
        self.count = count
        self.field = field
        self.inner = inner or PolicyOne()

    @property
    def min_replicas(self) -> int:
        return self.count * self.inner.min_replicas

    def validate(self, team: list[LocalityData]) -> bool:
        groups: dict[Optional[str], list[LocalityData]] = {}
        for loc in team:
            groups.setdefault(loc.get(self.field), []).append(loc)
        # None (unset field) never counts as a distinct satisfied group
        ok_groups = sum(
            1
            for key, members in groups.items()
            if key is not None and self.inner.validate(members)
        )
        return ok_groups >= self.count

    def __repr__(self):
        return f"PolicyAcross({self.count}, {self.field!r}, {self.inner!r})"


class PolicyUnsatisfiableError(ValueError):
    pass


def build_team(
    localities: dict[int, LocalityData],
    policy,
    *,
    exclude: frozenset = frozenset(),
    prefer: tuple = (),
) -> tuple:
    """Pick a minimal team of server ids satisfying `policy`.

    Exhaustive minimal-size search in preference order: the first
    satisfying combination of exactly policy.min_replicas servers wins
    (complete — any satisfying superset contains a min-size satisfying
    subset). Worst case O(C(n, r)) validate calls; topologies here are
    small. Raises PolicyUnsatisfiableError if no subset of the live
    topology can satisfy the policy — recruitment must fail loudly,
    never silently under-replicate.
    """
    candidates = [s for s in localities if s not in exclude]
    ordered = [s for s in prefer if s in candidates] + [
        s for s in sorted(candidates) if s not in prefer
    ]
    size = policy.min_replicas
    if size <= len(ordered):
        for combo in itertools.combinations(ordered, size):
            if policy.validate([localities[s] for s in combo]):
                return tuple(sorted(combo))
    raise PolicyUnsatisfiableError(
        f"{policy!r} unsatisfiable over {len(candidates)} servers"
    )


def validate_team(
    team: tuple, localities: dict[int, LocalityData], policy
) -> bool:
    return policy.validate([localities[s] for s in team if s in localities])
