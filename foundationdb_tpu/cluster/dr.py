"""DR: continuous replication into a second cluster + switchover.

The role of `fdbclient/DatabaseBackupAgent.actor.cpp` (fdbdr): an agent
pulls the primary's mutation log and applies it to a DESTINATION cluster
through ordinary transactions, keeping the destination a slightly-lagged
copy. The destination stays locked against client writes while DR runs
(applying a log onto a diverging database would corrupt both); on
switchover the agent drains to the primary's final version, verifies,
and unlocks the destination — which then takes over as the primary.

Mechanics here:

* The agent registers as a tlog consumer on the source (same peek/pop
  protocol the backup worker and storage servers use) and applies each
  version's mutations to the destination inside one transaction.
* The applied watermark is committed WITH each apply batch at
  `\\xff/dr/applied` on the destination — apply+watermark are atomic, so
  a restarted agent resumes exactly where the destination really is
  (the reference's logVersion/applyMutations bookkeeping).
* `lock()` / `unlock()` write `\\xff/dr/locked` on the destination and
  the client layer refuses ordinary commits while it is set (the
  reference's databaseLocked machinery, fdbclient/NativeAPI commit
  checks against `\\xff/dbLocked`).
"""

from __future__ import annotations

from typing import Optional

from foundationdb_tpu.runtime.flow import ActorCancelled
from foundationdb_tpu.utils.trace import TraceEvent

LOCK_KEY = b"\xff/dr/locked"
APPLIED_KEY = b"\xff/dr/applied"


from foundationdb_tpu.cluster.commit_proxy import DatabaseLockedError


class DestinationLockedError(DatabaseLockedError):
    """Client writes are refused while DR owns the destination (a
    DatabaseLockedError subclass: one logical condition, one catchable
    type regardless of which layer refused)."""


class DrAgent:
    """Continuous source->destination replication (fdbdr's agent)."""

    def __init__(self, src_cluster, src_db, dst_db, *, name: str = "dr"):
        self.src = src_cluster
        self.src_db = src_db
        self.dst = dst_db
        self.name = name
        self.applied_version = 0   # last version applied WITH data
        self.caught_up_version = 0  # source log position fully consumed
        self._task = None
        self._error: Exception | None = None

    # -- destination lock (databaseLocked semantics) ---------------------

    async def lock_destination(self) -> None:
        t = self.dst.create_transaction()
        t.dr_bypass = True  # idempotent re-lock must not block itself
        t.set(LOCK_KEY, self.name.encode())
        await t.commit()
        self.dst.dr_locked = True

    async def unlock_destination(self) -> None:
        t = self.dst.create_transaction()
        t.dr_bypass = True  # the unlock write itself rides the lock
        t.clear(LOCK_KEY)
        await t.commit()
        self.dst.dr_locked = False

    # -- the replication loop --------------------------------------------

    async def start(self) -> None:
        """Lock the destination, snapshot pre-existing source data, then
        tail the source log from the snapshot version.

        Registration precedes the snapshot, so every mutation after the
        snapshot's read version is retained in the log; the tail starts
        strictly above the snapshot version, so nothing is applied twice
        (atomics are not idempotent). A fresh agent over an already-
        primed destination resumes from its durable watermark instead.
        """
        from foundationdb_tpu.cluster.tlog import LOG_STREAM_TAG

        await self.lock_destination()
        sched = self.src.sched
        tlog = self.src.tlog
        tlog.register_consumer(self.name)

        t = self.dst.create_transaction()
        applied = await t.get(APPLIED_KEY)
        if applied is not None:
            self.applied_version = int(applied)
        else:
            # initial snapshot: pre-start source data is not in the log
            # (storage already consumed it) — copy it, then tail above
            # the snapshot's read version (FileBackupAgent's range
            # snapshot + log semantics compressed to one pass)
            ts = self.src_db.create_transaction()
            rv = await ts.get_read_version()
            data = await ts.get_range(b"", b"\xff")
            td = self.dst.create_transaction()
            td.dr_bypass = True
            # The copy must start from an empty destination: any
            # pre-existing destination key absent on the source would
            # survive a bare set-loop and silently diverge the replica
            # (the reference verifies an empty destination before
            # priming).
            td.clear_range(b"", b"\xff")
            for k, v in data:
                td.set(k, v)
            td.set(APPLIED_KEY, str(rv).encode())
            await td.commit()
            self.applied_version = rv
        self.caught_up_version = self.applied_version

        async def pull():
            try:
                after = self.applied_version
                while True:
                    got, log_version = await tlog.peek(LOG_STREAM_TAG, after)
                    entries = {v: msgs for v, msgs in got if msgs}
                    for v in sorted(entries):
                        await self._apply_one(v, entries[v])
                    after = max(log_version, max(entries, default=0))
                    # versions without mutations (empty commits) advance
                    # the caught-up watermark without an apply
                    self.caught_up_version = after
                    tlog.pop(LOG_STREAM_TAG, after, consumer=self.name)
                    await tlog.version.when_at_least(after + 1)
            except ActorCancelled:
                raise
            except Exception as e:
                # surface apply failures: drain_to re-raises instead of
                # spinning forever on a dead agent
                self._error = e
                raise

        self._task = sched.spawn(pull(), name=f"{self.name}-agent")

    async def _apply_one(self, version: int, mutations: list) -> None:
        """One source version -> one destination transaction (mutations +
        watermark together, so resume is exact)."""
        t = self.dst.create_transaction()
        t.dr_bypass = True  # the agent itself may write while locked
        for m in mutations:
            kind = m[0]
            if kind == "set":
                t.set(m[1], m[2])
            elif kind == "clear":
                t.clear_range(m[1], m[2])
            elif kind == "atomic":
                t.atomic_op(m[1], m[2], m[3])
            # vs_key/vs_value arrive already transformed by the source
        t.set(APPLIED_KEY, str(version).encode())
        await t.commit()
        self.applied_version = version

    async def drain_to(self, version: int) -> None:
        """Wait until everything at or below `version` is consumed (data
        versions applied; empty versions just advance the watermark).
        Raises if the agent task died."""
        while self.caught_up_version < version:
            if self._error is not None:
                raise self._error
            await self.src.sched.delay(0.01)

    async def switchover(self) -> int:
        """LOCK THE SOURCE, drain to its final version, then hand the
        destination over (unlock) — the reference's atomic switchover
        order. Commits racing the lock either land before it (drained)
        or fail database_locked; nothing acknowledged is lost. The
        retired source stays locked.
        """
        tl = self.src_db.create_transaction()
        tl.dr_bypass = True
        tl.set(LOCK_KEY, (self.name + "-switchover").encode())
        await tl.commit()
        # pipelined batches admitted before the lock became visible can
        # still commit ABOVE the lock version; one lock-aware sentinel
        # PINNED to every proxy serializes behind them (per-proxy batch
        # chains), so everything acknowledged lands at/below the final
        # version we drain to. Pinning, not round-robin adjacency:
        # concurrent traffic advances the shared pointer, so counting
        # commits does not fence every proxy (code review r5 — the
        # same defect class fixed in backup's stream barrier)
        for proxy in list(self.src.commit_proxies):
            sent = self.src_db.create_transaction()
            sent.dr_bypass = True
            sent.set(LOCK_KEY + b"/fence", b"1")
            sent._pin_proxy = proxy
            await sent.commit()
        final = self.src.tlog.version.get()
        await self.drain_to(final)
        self.abandon()
        await self.unlock_destination()
        TraceEvent("DrSwitchover").detail("Version", final).log()
        return final

    def stop(self) -> None:
        """Pause the agent. The tlog consumer registration STAYS: the
        source keeps retaining the log tail for this DR relationship (a
        crashed agent must not lose data either — the reference persists
        the DR pop watermark the same way). A restarted agent resumes
        from the destination's durable watermark.
        """
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def abandon(self) -> None:
        """Tear the DR relationship down permanently: the source stops
        retaining log for it (post-switchover, or operator abort)."""
        self.stop()
        self.src.tlog.unregister_consumer(self.name)
